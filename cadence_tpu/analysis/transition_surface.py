"""Pass 1 — transition-surface checker.

The replay kernel (ops/replay.py replay_step_cols) mirrors the host
oracle's event-type × state transition function. Fuzz differentials
sample that surface; this pass covers it statically:

* **Kernel matrix** — abstract trace of ``replay_step_cols`` once per
  transition group (jaxpr level): the event row is fed in as 16
  independent column leaves, so per-column data flow survives into the
  jaxpr and "which state columns can this event type write, from which
  event columns" falls out of reachability over the equations. No
  device, no execution — just tracing.
* **Oracle table** — AST extraction (oracle_ast.py) of
  ``StateBuilder.apply_events``'s dispatch chain and the
  ``MutableState.replicate_*`` write sets, mapped onto schema columns.
* **Diff** — unhandled-by-kernel event types, dead transition blocks,
  per-group column/table writes outside the oracle's mask (and oracle
  writes the kernel misses).
* **Schema invariants** — column constants dense + unique per table,
  pack.py ``attrs[i]`` stores inside the EV_A window, and
  ``ROW_TS_COLS`` (the epoch-rebase set ``rebase_state_row`` shifts)
  exactly equal to the traced set of epoch-bearing columns. A stale
  entry here is the bug class the checkpoint ``transition_fingerprint``
  can only detect, never localize.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from .findings import Finding
from . import oracle_ast

# --------------------------------------------------------------------------
# Schema column-group reflection
# --------------------------------------------------------------------------

# (prefix, count constant) per column table — schema.py owns the tuple
# so a new table added there is automatically covered by this pass
from cadence_tpu.ops.schema import _COLUMN_GROUPS as COLUMN_GROUPS  # noqa: E402


def _schema_ns() -> dict:
    from cadence_tpu.ops import schema as S

    return vars(S)


def column_names(
    prefix: str, count_name: str, ns: Optional[dict] = None
) -> Dict[int, List[str]]:
    """{column value → constant names} for one prefix (a well-formed
    table has exactly one name per value 0..N-1)."""
    ns = ns if ns is not None else _schema_ns()
    out: Dict[int, List[str]] = {}
    for k, v in ns.items():
        if (
            k.startswith(prefix)
            and k != count_name
            and isinstance(v, int)
            and not isinstance(v, bool)
        ):
            out.setdefault(v, []).append(k)
    for names in out.values():
        names.sort()
    return out


def check_column_groups(ns: Optional[dict] = None) -> List[Finding]:
    """Density + uniqueness of the schema column constants."""
    ns = ns if ns is not None else _schema_ns()
    findings: List[Finding] = []
    for prefix, count_name in COLUMN_GROUPS:
        if count_name not in ns:
            findings.append(Finding(
                "SCHEMA-COLUMNS", f"schema:{prefix}",
                f"missing count constant {count_name}",
            ))
            continue
        n = ns[count_name]
        by_val = column_names(prefix, count_name, ns)
        for v, names in sorted(by_val.items()):
            if len(names) > 1:
                findings.append(Finding(
                    "SCHEMA-COLUMNS", f"schema:{prefix}{v}:dup",
                    f"column value {v} claimed by {', '.join(names)}",
                ))
            if not (0 <= v < n):
                findings.append(Finding(
                    "SCHEMA-COLUMNS", f"schema:{prefix}{v}:range",
                    f"{names[0]} = {v} outside [0, {count_name}={n})",
                ))
        missing = sorted(set(range(n)) - set(by_val))
        if missing:
            findings.append(Finding(
                "SCHEMA-COLUMNS", f"schema:{prefix}:dense",
                f"no constant for column value(s) {missing} "
                f"(table not dense under {count_name}={n})",
            ))
    return findings


def check_pack_attrs(pack_source: str, ns: Optional[dict] = None) -> List[Finding]:
    """Every ``attrs[i]`` store in pack_workflow must land inside the
    EV_A0..EV_A(window-1) event-row window."""
    ns = ns if ns is not None else _schema_ns()
    window = ns["EV_N"] - ns["EV_A0"]
    findings: List[Finding] = []
    for i in sorted(oracle_ast.extract_attr_indices(pack_source)):
        if not (0 <= i < window):
            findings.append(Finding(
                "SCHEMA-PACK-ATTR", f"pack:attrs[{i}]",
                f"pack_workflow stores attrs[{i}] but the event row has "
                f"only {window} attribute columns (EV_A0..EV_A{window - 1})",
            ))
    return findings


# --------------------------------------------------------------------------
# Kernel matrix: jaxpr trace of replay_step_cols per transition group
# --------------------------------------------------------------------------


def _carry_labels() -> tuple:
    """Label pytree mirroring ops.replay.state_to_cols output structure."""
    ns = _schema_ns()

    def names(prefix: str, count: str) -> List[str]:
        by_val = column_names(prefix, count, ns)
        return [by_val[i][0] for i in range(ns[count])]

    return (
        tuple(f"exec:{n}" for n in names("X_", "X_N")),
        "vh:event_id",
        "vh:version",
        "vh:len",
        tuple(f"activities:{n}" for n in names("AC_", "AC_N")),
        tuple(f"timers:{n}" for n in names("TI_", "TI_N")),
        tuple(f"children:{n}" for n in names("CH_", "CH_N")),
        tuple(f"cancels:{n}" for n in names("RC_", "RC_N")),
        tuple(f"signals:{n}" for n in names("SG_", "SG_N")),
    )


def _ev_labels() -> tuple:
    ns = _schema_ns()
    by_val = column_names("EV_", "EV_N", ns)
    return tuple(f"ev:{by_val[i][0]}" for i in range(ns["EV_N"]))


class _EvCols:
    """Duck-typed event tensor: ``ev[:, c]`` returns column leaf ``c``.

    replay_step_cols only ever does static column slices of the event
    row, so feeding the columns as independent leaves keeps per-column
    provenance visible in the jaxpr."""

    def __init__(self, cols: tuple) -> None:
        self._cols = cols

    def __getitem__(self, idx):
        return self._cols[idx[1]]


def _literal_type():
    try:
        from jax.extend.core import Literal  # jax >= 0.4.x new home
        return Literal
    except Exception:
        from jax.core import Literal
        return Literal


def _trace_written(types: Optional[tuple], batch: int = 4):
    """Trace replay_step_cols with a static type set; returns
    (written labels, {written label → input-label dependency set})."""
    import jax

    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.replay import replay_step_cols, state_to_cols

    caps = S.Capacities(
        max_events=8, max_activities=3, max_timers=2, max_children=2,
        max_request_cancels=2, max_signals_ext=2, max_version_items=2,
    )
    cols = state_to_cols(S.empty_state(batch, caps))
    ev_cols = tuple(
        np.zeros((batch,), np.int32) for _ in range(S.EV_N)
    )

    def fn(c, ev):
        return replay_step_cols(c, _EvCols(ev), types=types)

    closed = jax.make_jaxpr(fn)(cols, ev_cols)
    jaxpr = closed.jaxpr
    in_labels = list(
        jax.tree_util.tree_leaves((_carry_labels(), _ev_labels()))
    )
    Literal = _literal_type()
    env: dict = {}
    for var, lab in zip(jaxpr.invars, in_labels):
        env[var] = frozenset((lab,))
    empty: FrozenSet[str] = frozenset()

    def deps_of(atom) -> FrozenSet[str]:
        if isinstance(atom, Literal):
            return empty
        return env.get(atom, empty)

    for eqn in jaxpr.eqns:
        dep: FrozenSet[str] = empty
        for v in eqn.invars:
            dep = dep | deps_of(v)
        for ov in eqn.outvars:
            env[ov] = dep

    out_labels = list(jax.tree_util.tree_leaves(_carry_labels()))
    written: Set[str] = set()
    deps: Dict[str, FrozenSet[str]] = {}
    for i, (ov, lab) in enumerate(zip(jaxpr.outvars, out_labels)):
        if isinstance(ov, Literal) or ov is not jaxpr.invars[i]:
            written.add(lab)
            deps[lab] = deps_of(ov)
    return written, deps


@dataclasses.dataclass
class GroupTrace:
    types: Tuple[int, ...]           # event types gating the block
    written: Set[str]                # state labels written (beyond common)
    ts_cols: Set[str]                # labels whose value derives from a
                                     # timestamp-bearing event column


@dataclasses.dataclass
class KernelMatrix:
    common: Set[str]                 # preamble writes (every valid event)
    common_ts: Set[str]
    groups: List[GroupTrace]

    def handled_types(self) -> Set[int]:
        out: Set[int] = set()
        for g in self.groups:
            out.update(g.types)
        return out

    def ts_columns(self) -> Set[str]:
        out = set(self.common_ts)
        for g in self.groups:
            out.update(g.ts_cols)
        return out


def _ts_inputs_for(
    types: Sequence[int], rel_ts_attrs: Dict[str, Set[int]]
) -> Set[str]:
    """Event-column labels carrying epoch-relative timestamps for this
    group: EV_TS always, plus every EV_A{i} the packer fills from
    rel_ts() for a member type."""
    from cadence_tpu.core.enums import EventType

    out = {"ev:EV_TS"}
    for t in types:
        for i in rel_ts_attrs.get(EventType(t).name, ()):
            out.add(f"ev:EV_A{i}")
    return out


def kernel_matrix(
    rel_ts_attrs: Optional[Dict[str, Set[int]]] = None,
) -> KernelMatrix:
    """Trace every transition group; ``rel_ts_attrs`` comes from
    oracle_ast.extract_rel_ts_attrs over ops/pack.py (empty dict: only
    EV_TS counts as timestamp-bearing)."""
    from cadence_tpu.ops.replay import _type_groups

    rel_ts_attrs = rel_ts_attrs or {}
    common, common_deps = _trace_written(types=())
    common_ts_in = _ts_inputs_for([], rel_ts_attrs)
    common_ts = {
        lab for lab, d in common_deps.items() if d & common_ts_in
    }
    groups: List[GroupTrace] = []
    for g in _type_groups():
        types = tuple(sorted(int(t) for t in g))
        written, deps = _trace_written(types=types)
        ts_in = _ts_inputs_for(types, rel_ts_attrs)
        groups.append(GroupTrace(
            types=types,
            written=written - common,
            ts_cols={
                lab for lab, d in deps.items()
                if d & ts_in and lab not in common
            },
        ))
    return KernelMatrix(common=common, common_ts=common_ts, groups=groups)


def kernel_handled_types() -> Set[int]:
    """Event types with a transition block in the kernel — no trace
    needed, the group table is the source of truth."""
    from cadence_tpu.ops.replay import _type_groups

    return {int(t) for g in _type_groups() for t in g}


# --------------------------------------------------------------------------
# Oracle table → schema columns
# --------------------------------------------------------------------------

# MutableState.execution_info field → kernel exec column label.
EXEC_FIELD_TO_COL = {
    "state": "exec:X_STATE",
    "close_status": "exec:X_CLOSE_STATUS",
    "next_event_id": "exec:X_NEXT_EVENT_ID",
    "last_first_event_id": "exec:X_LAST_FIRST_EVENT_ID",
    "last_event_task_id": "exec:X_LAST_EVENT_TASK_ID",
    "last_processed_event": "exec:X_LAST_PROCESSED_EVENT",
    "start_timestamp": "exec:X_START_TS",
    "workflow_timeout": "exec:X_WORKFLOW_TIMEOUT",
    "decision_timeout_value": "exec:X_DECISION_TIMEOUT_VALUE",
    "decision_version": "exec:X_DEC_VERSION",
    "decision_schedule_id": "exec:X_DEC_SCHEDULE_ID",
    "decision_started_id": "exec:X_DEC_STARTED_ID",
    "decision_timeout": "exec:X_DEC_TIMEOUT",
    "decision_attempt": "exec:X_DEC_ATTEMPT",
    "decision_scheduled_timestamp": "exec:X_DEC_SCHEDULED_TS",
    "decision_started_timestamp": "exec:X_DEC_STARTED_TS",
    "decision_original_scheduled_timestamp":
        "exec:X_DEC_ORIGINAL_SCHEDULED_TS",
    "cancel_requested": "exec:X_CANCEL_REQUESTED",
    "signal_count": "exec:X_SIGNAL_COUNT",
    "attempt": "exec:X_ATTEMPT",
    "has_retry_policy": "exec:X_HAS_RETRY_POLICY",
    "completion_event_batch_id": "exec:X_COMPLETION_EVENT_BATCH_ID",
    "initiated_id": "exec:X_PARENT_INITIATED_ID",
    "expiration_time": "exec:X_WF_EXPIRATION_TS",
}

# Host-only execution_info fields: strings, payloads, client metadata,
# retry-policy details kept host-side, persistence bookkeeping. Writes
# here have no device column, by design (the side table carries them).
EXEC_FIELD_IGNORE = {
    "domain_id", "workflow_id", "run_id", "parent_domain_id",
    "parent_workflow_id", "parent_run_id", "task_list",
    "workflow_type_name", "execution_context", "last_updated_timestamp",
    "create_request_id", "decision_request_id", "cancel_request_id",
    "sticky_task_list", "sticky_schedule_to_start_timeout",
    "client_library_version", "client_feature_version", "client_impl",
    "auto_reset_points", "memo", "search_attributes",
    "initial_interval", "backoff_coefficient", "maximum_interval",
    "maximum_attempts", "non_retriable_errors", "branch_token",
    "cron_schedule", "expiration_seconds",
    "first_decision_backoff_deadline", "history_size",
}


@dataclasses.dataclass
class OracleEntry:
    handlers: Tuple[str, ...]
    is_noop: bool
    tables: Set[str]          # pending-map tables touched
    exec_cols: Set[str]       # mapped exec column labels
    unmapped_fields: Set[str]  # exec fields neither mapped nor ignored

    def device_writes(self) -> Set[str]:
        return set(self.exec_cols) | set(self.tables)


def oracle_table(
    state_builder_source: str, mutable_state_source: str
) -> Dict[str, OracleEntry]:
    """{EventType name → oracle write surface in schema terms}."""
    dispatch = oracle_ast.extract_event_dispatch(state_builder_source)
    writes = oracle_ast.extract_replicate_writes(mutable_state_source)
    out: Dict[str, OracleEntry] = {}
    for tname, branch in dispatch.items():
        tables: Set[str] = set()
        exec_cols: Set[str] = set()
        unmapped: Set[str] = set()
        for h in branch.handler_calls:
            ws = writes.get(h)
            if ws is None:
                continue
            tables |= ws.tables
            for f in ws.exec_fields:
                if f in EXEC_FIELD_TO_COL:
                    exec_cols.add(EXEC_FIELD_TO_COL[f])
                elif f not in EXEC_FIELD_IGNORE:
                    unmapped.add(f)
        out[tname] = OracleEntry(
            handlers=branch.handler_calls,
            is_noop=branch.is_noop,
            tables=tables,
            exec_cols=exec_cols,
            unmapped_fields=unmapped,
        )
    return out


def _split_kernel_writes(written: Set[str]) -> Tuple[Set[str], Set[str]]:
    """(exec column labels, pending-map tables) of a kernel write set.
    Slot tables are compared at table granularity: the kernel writes
    whole rows under one-hot masks, the oracle mutates map entries —
    per-field comparison across that boundary would only mirror the
    kernel back at itself."""
    exec_cols = {w for w in written if w.startswith("exec:")}
    tables = {
        w.split(":", 1)[0]
        for w in written
        if w.split(":", 1)[0] in (
            "activities", "timers", "children", "cancels", "signals"
        )
    }
    return exec_cols, tables


def diff_surface(
    kmat: KernelMatrix,
    otable: Dict[str, OracleEntry],
    pack_handled: Optional[Set[str]] = None,
) -> List[Finding]:
    """Diff the kernel matrix against the oracle table."""
    from cadence_tpu.core.enums import EventType

    findings: List[Finding] = []
    handled = kmat.handled_types()
    handled_names = {EventType(t).name for t in handled}

    # oracle handlers without unmapped-field contract coverage
    for tname, entry in sorted(otable.items()):
        if entry.unmapped_fields:
            findings.append(Finding(
                "SURFACE-UNMAPPED-FIELD", f"surface:{tname}:unmapped",
                f"oracle handler(s) {', '.join(entry.handlers)} write "
                f"execution_info fields {sorted(entry.unmapped_fields)} "
                "that are neither mapped to a device column nor in the "
                "host-only ignore set — extend "
                "analysis.transition_surface.EXEC_FIELD_TO_COL",
            ))

    # unhandled-by-kernel: the oracle mutates device-mapped state for a
    # type the kernel has no transition block for
    for tname, entry in sorted(otable.items()):
        if tname in handled_names:
            continue
        if entry.device_writes():
            findings.append(Finding(
                "SURFACE-UNHANDLED", f"surface:{tname}:unhandled",
                f"event type {tname} writes {sorted(entry.device_writes())} "
                "in the host oracle but has no kernel transition block",
            ))

    # dead transition blocks: kernel block for a type the oracle
    # dispatch chain does not even accept
    for t in sorted(handled):
        tname = EventType(t).name
        if tname not in otable:
            findings.append(Finding(
                "SURFACE-DEAD-BLOCK", f"surface:{tname}:dead",
                f"kernel has a transition block for {tname} but the "
                "oracle dispatch chain does not handle it",
            ))

    # pack-layer coverage: every oracle-handled type must be packable
    if pack_handled is not None:
        for tname in sorted(otable):
            if tname not in pack_handled:
                findings.append(Finding(
                    "SURFACE-PACK-UNKNOWN", f"surface:{tname}:pack",
                    f"oracle handles {tname} but pack_workflow's dispatch "
                    "chain would reject it (PackError: unknown event type)",
                ))

    # per-group mask diff
    for g in kmat.groups:
        names = sorted(EventType(t).name for t in g.types)
        anchor_base = f"surface:{names[0]}"
        k_exec, k_tables = _split_kernel_writes(g.written)
        o_exec: Set[str] = set()
        o_tables: Set[str] = set()
        for t in g.types:
            entry = otable.get(EventType(t).name)
            if entry is None:
                continue
            o_exec |= entry.exec_cols
            o_tables |= entry.tables
        # columns in the kernel's common preamble (written for EVERY
        # valid event) can never be "missing" from a group
        common_exec, common_tables = _split_kernel_writes(kmat.common)
        extra = sorted((k_exec - o_exec) | (k_tables - o_tables))
        missing = sorted(
            (o_exec - k_exec - common_exec)
            | (o_tables - k_tables - common_tables)
        )
        if extra:
            findings.append(Finding(
                "SURFACE-EXTRA-WRITE", f"{anchor_base}:extra",
                f"kernel group {names} writes {extra} which the oracle "
                "handlers never touch (write outside the type's mask)",
            ))
        if missing:
            findings.append(Finding(
                "SURFACE-MISSING-WRITE", f"{anchor_base}:missing",
                f"oracle handlers for {names} write {missing} which the "
                "kernel group never writes",
            ))
    return findings


def _assoc_write_names(written: Set[str]) -> Set[str]:
    """Kernel write labels → the names ASSOC_COVERAGE declares: exec
    and vh labels stay per-column, slot tables collapse to table names
    (the emission derives whole-row masked writes per table)."""
    out: Set[str] = set()
    for w in written:
        head = w.split(":", 1)[0]
        if head in ("activities", "timers", "children", "cancels",
                    "signals"):
            out.add(head)
        else:
            out.add(w)
    return out


def check_assoc_coverage(kmat: KernelMatrix) -> List[Finding]:
    """ASSOC-UNPROVEN — prove the affine decomposition covers the traced
    write matrix.

    The parallel-in-time replay (ops/assoc.py) re-derives every kernel
    transition as a composable update; its declared coverage
    (``ASSOC_COVERAGE`` + ``ASSOC_COMMON``) is diffed here against the
    *traced* writes of replay_step_cols. A new transition block (or a
    new column in an existing block) that the emission does not cover
    fails CI instead of silently diverging between the sequential and
    associative kernels; the runtime classifier additionally routes any
    type outside ``assoc_types()`` to the sequential fallback. Stale
    ``schema.UPDATE_ALGEBRA`` entries (naming cells no emission covers)
    are flagged too.
    """
    from cadence_tpu.core.enums import EventType
    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.assoc import (
        ASSOC_COMMON, ASSOC_COVERAGE, assoc_types,
    )

    findings: List[Finding] = []
    provable = assoc_types()

    common = _assoc_write_names(kmat.common)
    miss = sorted(common - ASSOC_COMMON)
    if miss:
        findings.append(Finding(
            "ASSOC-UNPROVEN", "assoc:common",
            f"the kernel preamble writes {miss} which the affine "
            "decomposition's common coverage (ops/assoc.py ASSOC_COMMON)"
            " does not declare — replay_assoc would silently diverge",
        ))

    declared: Set[str] = set(ASSOC_COMMON)
    for g in kmat.groups:
        names = sorted(EventType(t).name for t in g.types)
        key = tuple(sorted(int(t) for t in g.types))
        cov = ASSOC_COVERAGE.get(key)
        bad_types = sorted(
            EventType(t).name for t in g.types if int(t) not in provable
        )
        if bad_types:
            findings.append(Finding(
                "ASSOC-UNPROVEN", f"assoc:{names[0]}:types",
                f"event type(s) {bad_types} have a kernel transition "
                "block but are outside assoc_types() — the associative "
                "path would mis-classify them as no-ops",
            ))
        if cov is None:
            findings.append(Finding(
                "ASSOC-UNPROVEN", f"assoc:{names[0]}:group",
                f"kernel transition group {names} has no declared "
                "affine coverage (ops/assoc.py ASSOC_COVERAGE) — its "
                "writes are unproven for the associative path",
            ))
            continue
        declared |= cov
        miss = sorted(_assoc_write_names(g.written) - cov - ASSOC_COMMON)
        if miss:
            findings.append(Finding(
                "ASSOC-UNPROVEN", f"assoc:{names[0]}:writes",
                f"kernel group {names} writes {miss} which its declared "
                "affine coverage does not include — extend the "
                "ops/assoc.py emission (and ASSOC_COVERAGE) or route "
                "the type to the sequential fallback",
            ))

    for label in sorted(S.UPDATE_ALGEBRA):
        if label not in declared:
            findings.append(Finding(
                "ASSOC-UNPROVEN", f"assoc:algebra:{label}",
                f"schema.UPDATE_ALGEBRA declares {label!r} "
                f"({S.UPDATE_ALGEBRA[label]}) but no transition group's "
                "assoc coverage writes it — stale metadata",
            ))
    return findings


def check_ts_coverage(
    kmat: KernelMatrix, ns: Optional[dict] = None
) -> List[Finding]:
    """ROW_TS_COLS (what rebase_state_row shifts between epochs) must
    equal the traced set of epoch-bearing state columns."""
    ns = ns if ns is not None else _schema_ns()
    row_ts = ns["ROW_TS_COLS"]
    field_prefix = {
        "exec_info": ("exec", "X_", "X_N"),
        "activities": ("activities", "AC_", "AC_N"),
        "timers": ("timers", "TI_", "TI_N"),
        "children": ("children", "CH_", "CH_N"),
        "cancels": ("cancels", "RC_", "RC_N"),
        "signals": ("signals", "SG_", "SG_N"),
    }
    declared: Set[str] = set()
    for field, cols in row_ts.items():
        label, prefix, count = field_prefix[field]
        by_val = column_names(prefix, count, ns)
        for c in cols:
            declared.add(f"{label}:{by_val[c][0]}")
    traced = {
        c for c in kmat.ts_columns()
        if not c.startswith("vh:")  # vh carries ids/versions, never ts
    }
    findings: List[Finding] = []
    for c in sorted(traced - declared):
        findings.append(Finding(
            "SURFACE-TS-UNCOVERED", f"ts:{c}",
            f"{c} derives from an epoch-relative timestamp in the kernel "
            "but is missing from schema.ROW_TS_COLS — rebase_state_row "
            "will not shift it and cross-epoch checkpoint resume will "
            "read a stale absolute time",
        ))
    for c in sorted(declared - traced):
        findings.append(Finding(
            "SURFACE-TS-STALE", f"ts:{c}",
            f"schema.ROW_TS_COLS declares {c} epoch-bearing but no "
            "kernel transition derives it from a timestamp column",
        ))
    return findings


# --------------------------------------------------------------------------
# Orchestration + matrix artifact
# --------------------------------------------------------------------------


def _read(repo_root: str, rel: str) -> str:
    with open(os.path.join(repo_root, rel)) as f:
        return f.read()


def build(repo_root: str):
    """(kernel matrix, oracle table, pack-handled names, rel_ts attrs)."""
    sb_src = _read(repo_root, "cadence_tpu/core/state_builder.py")
    ms_src = _read(repo_root, "cadence_tpu/core/mutable_state.py")
    pack_src = _read(repo_root, "cadence_tpu/ops/pack.py")
    rel_ts = oracle_ast.extract_rel_ts_attrs(pack_src)
    kmat = kernel_matrix(rel_ts_attrs=rel_ts)
    otable = oracle_table(sb_src, ms_src)
    pack_handled = set(
        oracle_ast.extract_event_dispatch(
            pack_src, func_name="pack_workflow"
        )
    )
    return kmat, otable, pack_handled, rel_ts


def run(repo_root: str) -> List[Finding]:
    pack_src = _read(repo_root, "cadence_tpu/ops/pack.py")
    findings = check_column_groups()
    findings += check_pack_attrs(pack_src)
    kmat, otable, pack_handled, _ = build(repo_root)
    findings += diff_surface(kmat, otable, pack_handled=pack_handled)
    findings += check_ts_coverage(kmat)
    findings += check_assoc_coverage(kmat)
    return findings


def emit_matrix(repo_root: str, path: str) -> None:
    """Write the transition coverage matrix as a JSON build artifact
    (versioned via the shared artifact envelope, like the queue
    conflict matrix — downstream consumers validate schema_version +
    kind instead of guessing from the file name)."""
    from cadence_tpu.core.enums import EventType

    from .artifact import write_artifact

    kmat, otable, pack_handled, rel_ts = build(repo_root)
    doc = {
        "common": sorted(kmat.common),
        "common_ts": sorted(kmat.common_ts),
        "kernel_handled_types": sorted(
            EventType(t).name for t in kmat.handled_types()
        ),
        "pack_handled_types": sorted(pack_handled),
        "rel_ts_attrs": {k: sorted(v) for k, v in sorted(rel_ts.items())},
        "groups": [
            {
                "types": sorted(EventType(t).name for t in g.types),
                "written": sorted(g.written),
                "ts_columns": sorted(g.ts_cols),
            }
            for g in kmat.groups
        ],
        "oracle": {
            tname: {
                "handlers": list(e.handlers),
                "noop": e.is_noop,
                "tables": sorted(e.tables),
                "exec_cols": sorted(e.exec_cols),
            }
            for tname, e in sorted(otable.items())
        },
    }
    write_artifact(path, "transition_matrix", doc)
