"""Findings + baseline plumbing for the static-analysis gate.

A finding is identified by ``(rule, anchor)``. Anchors are built from
stable names (module path, class.method, event-type name, lock
attribute) — never line numbers — so a baseline entry survives
unrelated edits to the file it points at. Line numbers ride along in
the message for humans.

The baseline file (config/lint_baseline.json) records *accepted*
findings, each with a one-line justification. ``fnmatch`` patterns are
allowed in baseline anchors so a family of intentional findings (e.g.
every sqlite-store method doing I/O under the connection lock) is one
entry, not forty.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation.

    rule:    short rule id, e.g. "LOCK-BLOCKING" or "SURFACE-UNHANDLED".
    anchor:  stable identifier of the site, e.g.
             "runtime/shard.py:ShardContext.renew_range:_lock:update_shard".
    message: human-readable description (may include file:line).
    """

    rule: str
    anchor: str
    message: str

    @property
    def key(self) -> Tuple[str, str]:
        return (self.rule, self.anchor)

    def format(self) -> str:
        return f"[{self.rule}] {self.anchor}\n    {self.message}"


@dataclasses.dataclass
class BaselineEntry:
    rule: str
    anchor: str  # may be an fnmatch pattern
    justification: str = ""

    def matches(self, finding: Finding) -> bool:
        return self.rule == finding.rule and fnmatch.fnmatchcase(
            finding.anchor, self.anchor
        )


class Baseline:
    """Accepted-findings file: new findings fail the gate, accepted ones
    don't. Entries that match nothing are reported as stale (warning,
    not failure — a fixed finding shouldn't break the build)."""

    def __init__(self, entries: Optional[Sequence[BaselineEntry]] = None):
        self.entries: List[BaselineEntry] = list(entries or [])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            doc = json.load(f)
        return cls([
            BaselineEntry(
                rule=e["rule"],
                anchor=e["anchor"],
                justification=e.get("justification", ""),
            )
            for e in doc.get("findings", [])
        ])

    def save(self, path: str) -> None:
        doc = {
            "findings": [
                {
                    "rule": e.rule,
                    "anchor": e.anchor,
                    "justification": e.justification,
                }
                for e in self.entries
            ]
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=False)
            f.write("\n")

    def split(
        self, findings: Sequence[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """(new, accepted, stale_entries)."""
        new: List[Finding] = []
        accepted: List[Finding] = []
        used: set = set()
        for f in findings:
            hit = None
            for i, e in enumerate(self.entries):
                if e.matches(f):
                    hit = i
                    break
            if hit is None:
                new.append(f)
            else:
                accepted.append(f)
                used.add(hit)
        stale = [e for i, e in enumerate(self.entries) if i not in used]
        return new, accepted, stale


def dedupe(findings: Sequence[Finding]) -> List[Finding]:
    """Drop exact (rule, anchor) duplicates, keeping first occurrence."""
    seen: Dict[Tuple[str, str], bool] = {}
    out: List[Finding] = []
    for f in findings:
        if f.key not in seen:
            seen[f.key] = True
            out.append(f)
    return out
