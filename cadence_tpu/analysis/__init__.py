"""Static-analysis CI gate for the cadence-tpu kernel/runtime contract.

Five passes, run together by ``python -m cadence_tpu.analysis``:

1. **transition surface** (transition_surface.py) — the kernel's
   event-type × column write matrix, traced at jaxpr level, diffed
   against the host oracle's AST-extracted transition table and the
   ops/schema.py invariants (column density, EV_A windows, epoch-rebase
   coverage).
2. **jit hazards** (jit_hazards.py) — recompilation, host-sync,
   Python-branch and dtype-widening hazards over ops/ and the dispatch
   callers.
3. **lock order** (lock_order.py) — the runtime's lock graph:
   acquisition-order inversions and blocking work (store I/O, sleeps,
   joins, foreign waits) done while holding a lock.
4. **metrics** (metric_decl.py) — every literal metric emission under
   runtime/ops/matching/checkpoint must be declared in a
   utils/metrics_defs.py catalog (rule METRIC-UNDECLARED): the
   operator docs can never silently trail the code.
5. **queue effects** (queue_effects.py) — AST-derived effect
   footprints of every queue-task handler (transfer/timer/standby +
   the NDC apply path) diffed against the declared footprint table
   (runtime/queues/effects.py): rules QUEUE-EFFECT-UNKNOWN,
   QUEUE-CONFLICT-UNDECLARED, QUEUE-CROSS-WF. The footprints derive
   the task-type commutativity matrix (--emit-conflict-matrix) the
   future parallel-queue executor gates on.

Findings gate against a checked-in baseline
(config/lint_baseline.json): accepted findings carry a one-line
justification; anything new exits non-zero. See analysis/README.md for
per-rule docs and how to baseline.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .findings import Baseline, BaselineEntry, Finding, dedupe

PASSES = ("surface", "jit", "locks", "metrics", "queue")

# rule-id prefixes per pass — lets a --passes subset run scope the
# baseline to the rules that could actually fire, so entries belonging
# to skipped passes are not reported (or strict-failed) as stale
PASS_RULE_PREFIXES = {
    "surface": ("SURFACE-", "SCHEMA-", "ASSOC-"),
    "jit": ("JIT-", "PALLAS-"),
    "locks": ("LOCK-",),
    "metrics": ("METRIC-",),
    "queue": ("QUEUE-",),
}


def scope_baseline(baseline, passes):
    """Baseline restricted to entries whose rule belongs to ``passes``
    (None = all passes, returned unchanged). Entries with rules outside
    every known prefix only gate on full runs."""
    if passes is None:
        return baseline
    prefixes = tuple(
        p for name in passes for p in PASS_RULE_PREFIXES.get(name, ())
    )
    return Baseline([
        e for e in baseline.entries if e.rule.startswith(prefixes)
    ]) if prefixes else Baseline([])


def run_pass(name: str, repo_root: str) -> List[Finding]:
    if name == "surface":
        from . import transition_surface

        return transition_surface.run(repo_root)
    if name == "jit":
        from . import jit_hazards

        return jit_hazards.run(repo_root)
    if name == "locks":
        from . import lock_order

        return lock_order.run(repo_root)
    if name == "metrics":
        from . import metric_decl

        return metric_decl.run(repo_root)
    if name == "queue":
        from . import queue_effects

        return queue_effects.run(repo_root)
    raise ValueError(f"unknown pass {name!r} (have: {PASSES})")


def run_all(
    repo_root: str, passes: Optional[List[str]] = None
) -> Dict[str, List[Finding]]:
    """{pass name → deduped findings} over the real tree."""
    out: Dict[str, List[Finding]] = {}
    for name in passes or PASSES:
        out[name] = dedupe(run_pass(name, repo_root))
    return out


__all__ = [
    "Baseline", "BaselineEntry", "Finding", "PASSES",
    "PASS_RULE_PREFIXES", "dedupe", "run_all", "run_pass",
    "scope_baseline",
]
