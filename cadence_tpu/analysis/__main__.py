"""CLI for the static-analysis gate.

    python -m cadence_tpu.analysis [--baseline config/lint_baseline.json]
                                   [--passes surface,jit,locks,metrics]
                                   [--emit-matrix PATH]
                                   [--write-baseline PATH]
                                   [--root DIR]

Exit status: 0 when every finding is baselined (or none), 1 when new
findings exist, 2 on usage/internal error. Designed to run on CPU with
JAX_PLATFORMS=cpu in well under a minute — the kernel is *traced*, not
executed.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m cadence_tpu.analysis")
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        help="repo root (default: derived from this package's location)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="baseline JSON of accepted findings (config/lint_baseline.json)",
    )
    ap.add_argument(
        "--passes", default=None,
        help="comma-separated subset of passes (surface,jit,locks,metrics)",
    )
    ap.add_argument(
        "--emit-matrix", default=None, metavar="PATH",
        help="also write the transition coverage matrix JSON artifact",
    )
    ap.add_argument(
        "--write-baseline", default=None, metavar="PATH",
        help="write ALL current findings as a fresh baseline "
        "(justifications stubbed 'TODO') and exit 0",
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="only print the summary line and new findings",
    )
    args = ap.parse_args(argv)

    from . import Baseline, BaselineEntry, run_all

    passes = args.passes.split(",") if args.passes else None
    t0 = time.monotonic()
    try:
        by_pass = run_all(args.root, passes=passes)
    except Exception as e:  # a broken tree must fail loudly, not pass
        print(f"analysis error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.emit_matrix:
        from . import transition_surface

        try:
            transition_surface.emit_matrix(args.root, args.emit_matrix)
        except Exception as e:
            print(
                f"analysis error writing matrix: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 2
        print(f"transition matrix -> {args.emit_matrix}")

    all_findings = [f for fs in by_pass.values() for f in fs]

    if args.write_baseline:
        bl = Baseline([
            BaselineEntry(rule=f.rule, anchor=f.anchor, justification="TODO")
            for f in all_findings
        ])
        bl.save(args.write_baseline)
        print(f"wrote {len(bl.entries)} baseline entries -> "
              f"{args.write_baseline}")
        return 0

    baseline = Baseline()
    if args.baseline:
        baseline = Baseline.load(args.baseline)
    new, accepted, stale = baseline.split(all_findings)

    for name, fs in by_pass.items():
        fresh = [f for f in fs if f in new]
        if not args.quiet:
            print(f"== pass {name}: {len(fs)} finding(s), "
                  f"{len(fs) - len(fresh)} baselined ==")
        for f in fresh:
            print(f.format())
    for e in stale:
        print(f"warning: stale baseline entry [{e.rule}] {e.anchor} "
              "matched nothing (fixed? remove it)", file=sys.stderr)

    dt = time.monotonic() - t0
    print(
        f"cadence_tpu.analysis: {len(all_findings)} finding(s), "
        f"{len(accepted)} baselined, {len(new)} new, "
        f"{len(stale)} stale baseline entr(ies) in {dt:.1f}s"
    )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
