"""CLI for the static-analysis gate.

    python -m cadence_tpu.analysis [--baseline config/lint_baseline.json]
                                   [--passes surface,jit,locks,metrics,queue]
                                   [--emit-matrix PATH]
                                   [--emit-conflict-matrix PATH]
                                   [--emit-lock-graph PATH [--witness PATH]]
                                   [--strict-stale]
                                   [--write-baseline PATH]
                                   [--root DIR]

Exit status: 0 when every finding is baselined (or none), 1 when new
findings exist, 2 on usage/internal error. Designed to run on CPU with
JAX_PLATFORMS=cpu in well under a minute — the kernel is *traced*, not
executed.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m cadence_tpu.analysis")
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        help="repo root (default: derived from this package's location)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="baseline JSON of accepted findings (config/lint_baseline.json)",
    )
    ap.add_argument(
        "--passes", default=None,
        help="comma-separated subset of passes "
        "(surface,jit,locks,metrics,queue)",
    )
    ap.add_argument(
        "--emit-matrix", default=None, metavar="PATH",
        help="also write the transition coverage matrix JSON artifact",
    )
    ap.add_argument(
        "--emit-conflict-matrix", default=None, metavar="PATH",
        help="also write the queue-task commutativity matrix JSON "
        "artifact (the parallel-queue executor's gate)",
    )
    ap.add_argument(
        "--emit-lock-graph", default=None, metavar="PATH",
        help="also write the lock-graph JSON artifact: static lock "
        "inventory + acquisition-order edges, annotated "
        "observed/never-observed against the latest runtime witness "
        "(build/lock_witness.json from a sanitized suite run)",
    )
    ap.add_argument(
        "--witness", default=None, metavar="PATH",
        help="runtime lock-witness artifact for --emit-lock-graph "
        "annotations (default: build/lock_witness.json under --root)",
    )
    ap.add_argument(
        "--strict-stale", action="store_true",
        help="treat stale baseline entries as errors (exit 1) instead "
        "of warnings, so dead entries can't accumulate silently",
    )
    ap.add_argument(
        "--write-baseline", default=None, metavar="PATH",
        help="write ALL current findings as a fresh baseline "
        "(justifications stubbed 'TODO') and exit 0",
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="only print the summary line and new findings",
    )
    args = ap.parse_args(argv)

    from . import Baseline, BaselineEntry, run_all, scope_baseline

    passes = args.passes.split(",") if args.passes else None
    t0 = time.monotonic()
    try:
        by_pass = run_all(args.root, passes=passes)
    except Exception as e:  # a broken tree must fail loudly, not pass
        print(f"analysis error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2

    if args.emit_matrix:
        from . import transition_surface

        try:
            transition_surface.emit_matrix(args.root, args.emit_matrix)
        except Exception as e:
            print(
                f"analysis error writing matrix: {type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 2
        print(f"transition matrix -> {args.emit_matrix}")

    if args.emit_conflict_matrix:
        from . import queue_effects

        try:
            queue_effects.emit_conflict_matrix(
                args.root, args.emit_conflict_matrix
            )
        except Exception as e:
            print(
                f"analysis error writing conflict matrix: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 2
        print(f"queue conflict matrix -> {args.emit_conflict_matrix}")

    if args.emit_lock_graph:
        from . import lock_order

        try:
            doc = lock_order.emit_lock_graph(
                args.root, args.emit_lock_graph,
                witness_path=args.witness,
                baseline_path=args.baseline,
            )
        except Exception as e:
            print(
                f"analysis error writing lock graph: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
            return 2
        print(
            f"lock graph -> {args.emit_lock_graph} "
            f"(witness: {doc['witness']})"
        )

    all_findings = [f for fs in by_pass.values() for f in fs]

    if args.write_baseline:
        bl = Baseline([
            BaselineEntry(rule=f.rule, anchor=f.anchor, justification="TODO")
            for f in all_findings
        ])
        bl.save(args.write_baseline)
        print(f"wrote {len(bl.entries)} baseline entries -> "
              f"{args.write_baseline}")
        return 0

    baseline = Baseline()
    if args.baseline:
        baseline = Baseline.load(args.baseline)
    # a --passes subset must not count the skipped passes' baseline
    # entries as stale (a `--passes queue` run would otherwise strict-
    # fail on every SURFACE-*/LOCK-* entry)
    baseline = scope_baseline(baseline, passes)
    new, accepted, stale = baseline.split(all_findings)

    for name, fs in by_pass.items():
        fresh = [f for f in fs if f in new]
        if not args.quiet:
            print(f"== pass {name}: {len(fs)} finding(s), "
                  f"{len(fs) - len(fresh)} baselined ==")
        for f in fresh:
            print(f.format())
    stale_word = "error" if args.strict_stale else "warning"
    for e in stale:
        print(f"{stale_word}: stale baseline entry [{e.rule}] {e.anchor} "
              "matched nothing (fixed? remove it)", file=sys.stderr)

    dt = time.monotonic() - t0
    print(
        f"cadence_tpu.analysis: {len(all_findings)} finding(s), "
        f"{len(accepted)} baselined, {len(new)} new, "
        f"{len(stale)} stale baseline entr(ies) in {dt:.1f}s"
    )
    if new:
        return 1
    if stale and args.strict_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
