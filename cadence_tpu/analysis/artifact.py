"""Versioned JSON build artifacts for the analysis gate.

One writer for every machine-readable artifact the gate emits
(``--emit-matrix``, ``--emit-conflict-matrix``): a ``schema_version``
plus an ``artifact`` kind ride at the top of the document so downstream
consumers — the future parallel-queue executor, bench tooling — can
validate what they load instead of guessing from the file name.
``load_artifact`` is that validation, shared so the checks can't drift
per consumer.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

SCHEMA_VERSION = 1


def write_artifact(path: str, kind: str, payload: Dict) -> None:
    """Write ``payload`` wrapped with the artifact envelope. The
    envelope keys win on collision — a payload must not be able to
    spoof its own schema version."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    doc = dict(payload)
    doc["schema_version"] = SCHEMA_VERSION
    doc["artifact"] = kind
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


def load_artifact(path: str, kind: Optional[str] = None) -> Dict:
    """Load + validate an artifact: the schema version must be one this
    code understands and (when given) the kind must match — a consumer
    handed the wrong file fails loudly instead of misreading it."""
    with open(path) as f:
        doc = json.load(f)
    version = doc.get("schema_version")
    if version != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: artifact schema_version {version!r} "
            f"(this build understands {SCHEMA_VERSION})"
        )
    if kind is not None and doc.get("artifact") != kind:
        raise ValueError(
            f"{path}: artifact kind {doc.get('artifact')!r}, "
            f"expected {kind!r}"
        )
    return doc
