"""Pass 3 — concurrency lint: lock graph + blocking-calls-under-lock.

The runtime is a thread pile: queue pumps, worker pools, ack sweeps,
replication appliers, the shard sequencer. This pass builds, purely
from the AST:

* a **lock inventory** — ``self.x = threading.Lock()/RLock()/
  Condition()`` attributes per class, plus local locks;
* a **lock-order graph** — an edge A→B wherever B is acquired while A
  is held (nested ``with``, ``.acquire()``, or a same-class method call
  that acquires B), with cycle (inversion) detection;
* **blocking-call-under-lock** findings — store I/O (persistence
  managers, sqlite cursors), ``time.sleep``, ``.join()``, blocking
  queue ``get``/``put``, and ``.wait()`` on anything *other than the
  condition being held* (waiting on a held Condition releases it; an
  Event.wait under someone else's lock stalls every other holder).

Cross-class lock propagation: a call under a held lock whose receiver
is NOT ``self`` (``handle.shard.fence()``, ``c.acquire_shards()``) is
resolved by METHOD NAME against every class in scope. When the name
resolves unambiguously — exactly one scope class defines it, or every
defining class agrees it blocks — the callee's blocking work surfaces
as LOCK-CROSS-BLOCKING at the caller, and the callee's lock
acquisitions become cross-class edges in the inversion graph (a
coordinator holding its own lock while fencing a shard context now
participates in the same order proof as the context's lock). Names
defined by many scope classes with disagreeing behavior are skipped —
resolution is by name, not type inference, and a wrong guess would be
noise, not safety.

Known limits (documented, deliberate): remaining cross-class reasoning
is attribute-name heuristics (a call whose receiver chain mentions
``persistence``/store managers counts as I/O), and dynamic dispatch
through callbacks is matched by callable-attribute *name* (e.g.
``self._update_shard_ack(...)``). Non-blocking try-locks
(``acquire(blocking=False)``) are exempt.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# method names that are store/persistence I/O wherever they appear
STORE_METHODS = {
    "update_shard", "create_shard", "get_shard",
    "append_history_nodes", "read_history_branch", "new_history_branch",
    "get_workflow_execution", "update_workflow_execution",
    "create_workflow_execution", "delete_workflow_execution",
    "get_transfer_tasks", "get_timer_tasks",
    "range_complete_transfer_tasks", "range_complete_timer_tasks",
    "complete_transfer_task", "complete_timer_task",
    "list_domains", "get_domain", "update_domain",
    "put_checkpoint", "list_checkpoints", "list_tree_checkpoints",
    "delete_checkpoint", "prune_tree",
    "execute", "executemany", "executescript", "commit",
}

# receiver-chain substrings that mark a call as store I/O
STORE_RECEIVERS = ("persistence", "_conn", ".store", ".shard.")

ALWAYS_BLOCKING_ATTRS = {"sleep", "join"}

# lock-protocol attrs never treated as cross-class method calls
_LOCK_OPS = {"acquire", "release", "wait", "notify", "notify_all",
             "locked"}

# names shared with builtin container/string/file protocols: a
# same-named scope method is coincidence, not a resolution target
# (``failures.append(...)`` is a list, not TaskWriter.append)
_BUILTIN_METHOD_NAMES = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "get", "put", "add", "discard", "setdefault", "keys",
    "values", "items", "sort", "reverse", "count", "index", "copy",
    "split", "rsplit", "join", "strip", "lstrip", "rstrip", "replace",
    "format", "encode", "decode", "startswith", "endswith", "lower",
    "upper", "read", "write", "close", "flush", "seek",
}

# callable-attribute name fragments treated as blocking when invoked
BLOCKING_CALLABLE_HINTS = ("update_shard",)


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted string of an expression ("self._lock",
    "self.persistence.shard.update_shard", "ctx.lock")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    if isinstance(node, ast.Subscript):
        return f"{_dotted(node.value)}[]"
    return "<expr>"


@dataclasses.dataclass
class BlockingCall:
    receiver: str
    lineno: int
    why: str


def _blocking_reason(
    node: ast.Call, held: Tuple[str, ...], queue_attrs: Set[str]
) -> Optional[str]:
    """Why this call is blocking, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = _dotted(fn.value)
        attr = fn.attr
        if attr in ALWAYS_BLOCKING_ATTRS:
            return f"{recv}.{attr}() blocks"
        if attr == "wait":
            # waiting on the condition you hold releases it; anything
            # else parks the thread with the lock still held
            if recv in held:
                return None
            return f"{recv}.wait() parks the thread while locked"
        if attr == "acquire":
            for kw in node.keywords:
                if (
                    kw.arg == "blocking"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                ):
                    return None
            if recv in held:
                return None  # re-entrant acquire of the held lock
            return None  # plain acquire handled as a lock edge, not I/O
        if attr in ("get", "put") and recv.rsplit(".", 1)[-1] in queue_attrs:
            for kw in node.keywords:
                if kw.arg == "block" and isinstance(
                    kw.value, ast.Constant
                ) and kw.value.value is False:
                    return None
                if kw.arg == "timeout" and isinstance(
                    kw.value, ast.Constant
                ) and kw.value.value == 0:
                    return None
            return f"queue {recv}.{attr}() can block on capacity"
        if attr in STORE_METHODS:
            return f"store I/O {recv}.{attr}(...)"
        if any(s in recv for s in STORE_RECEIVERS):
            # receiver chain names a store manager: any method on it is
            # I/O even if the name isn't in STORE_METHODS
            return f"store I/O {recv}.{attr}(...)"
    elif isinstance(fn, ast.Name):
        pass
    # callable attributes by name: self._update_shard_ack(...)
    if isinstance(fn, ast.Attribute) and any(
        h in fn.attr for h in BLOCKING_CALLABLE_HINTS
    ):
        return f"callable {_dotted(fn)}(...) persists shard state"
    return None


@dataclasses.dataclass
class MethodInfo:
    qualname: str               # Class.method or function name
    acquires: Set[str]          # lock attrs acquired anywhere (self-relative)
    blocking: List[BlockingCall]            # blocking calls ANYWHERE in body
    under_lock: List[Tuple[str, BlockingCall]]   # (held lock, call)
    edges: List[Tuple[str, str, int]]       # (held, acquired, lineno)
    self_calls_under_lock: List[Tuple[str, str, int]]  # (held, method, line)
    # (held lock, method name, lineno, receiver) for non-self receivers
    # — resolved cross-class by name in collect_findings
    ext_calls_under_lock: List[Tuple[str, str, int, str]] = dataclasses.field(
        default_factory=list
    )


class _MethodVisitor(ast.NodeVisitor):
    def __init__(self, qualname: str, lock_names: Set[str],
                 queue_attrs: Set[str]) -> None:
        self.info = MethodInfo(
            qualname=qualname, acquires=set(), blocking=[],
            under_lock=[], edges=[], self_calls_under_lock=[],
        )
        self.lock_names = lock_names
        self.queue_attrs = queue_attrs
        self.held: List[str] = []

    # -- helpers -------------------------------------------------------

    def _is_known_lock(self, dotted: str) -> bool:
        last = dotted.rsplit(".", 1)[-1]
        return last in self.lock_names or _lockish_name(last)

    def _enter_lock(self, dotted: str, body, lineno: int) -> None:
        if self.held:
            self.info.edges.append((self.held[-1], dotted, lineno))
        self.info.acquires.add(dotted)
        self.held.append(dotted)
        for stmt in body:
            self.visit(stmt)
        self.held.pop()

    # -- visitors ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        lock_expr = None
        for item in node.items:
            d = _dotted(item.context_expr)
            if self._is_known_lock(d):
                lock_expr = d
                break
        if lock_expr is not None:
            self._enter_lock(lock_expr, node.body, node.lineno)
        else:
            self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        reason = _blocking_reason(node, tuple(self.held), self.queue_attrs)
        if reason is not None:
            call = BlockingCall(
                receiver=_dotted(node.func), lineno=node.lineno, why=reason
            )
            self.info.blocking.append(call)
            if self.held:
                self.info.under_lock.append((self.held[-1], call))
        # blocking .acquire() of another lock while one is held = edge
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            recv = _dotted(node.func.value)
            nonblocking = any(
                kw.arg == "blocking"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords
            )
            if not nonblocking and self._is_known_lock(recv):
                if self.held and recv != self.held[-1]:
                    self.info.edges.append(
                        (self.held[-1], recv, node.lineno)
                    )
                self.info.acquires.add(recv)
        # self.method(...) under a held lock → propagation candidate
        if (
            self.held
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            self.info.self_calls_under_lock.append(
                (self.held[-1], node.func.attr, node.lineno)
            )
        # any OTHER receiver's method under a held lock → cross-class
        # propagation candidate (resolved by name in collect_findings);
        # calls already classified blocking above are not re-recorded
        elif (
            self.held
            and isinstance(node.func, ast.Attribute)
            and reason is None
            and node.func.attr not in _LOCK_OPS
            and node.func.attr not in _BUILTIN_METHOD_NAMES
        ):
            recv = _dotted(node.func.value)
            if recv != "self" and not recv.startswith("super()"):
                self.info.ext_calls_under_lock.append(
                    (self.held[-1], node.func.attr, node.lineno, recv)
                )
        self.generic_visit(node)


def _lockish_name(name: str) -> bool:
    """Does this attribute name look like a lock/condition object?"""
    n = name.rsplit(".", 1)[-1]
    return (
        "lock" in n
        or n.lstrip("_") in ("cond", "condition", "cv")
        or n.endswith("_cond")
    )


@dataclasses.dataclass
class ClassAnalysis:
    module: str
    name: str
    lock_attrs: Set[str]
    queue_attrs: Set[str]
    methods: Dict[str, MethodInfo]


def _class_lock_attrs(cls: ast.ClassDef) -> Tuple[Set[str], Set[str]]:
    locks: Set[str] = set()
    queues: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not isinstance(v, ast.Call):
            continue
        fname = (
            v.func.attr if isinstance(v.func, ast.Attribute)
            else v.func.id if isinstance(v.func, ast.Name) else ""
        )
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                if fname in LOCK_FACTORIES:
                    locks.add(tgt.attr)
                elif fname == "Queue":
                    queues.add(tgt.attr)
            elif isinstance(tgt, ast.Name) and fname in LOCK_FACTORIES:
                locks.add(tgt.id)
    return locks, queues


def analyze_module(source: str, relmodule: str) -> List[ClassAnalysis]:
    tree = ast.parse(source)
    out: List[ClassAnalysis] = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        locks, queues = _class_lock_attrs(node)
        methods: Dict[str, MethodInfo] = {}
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                v = _MethodVisitor(
                    f"{node.name}.{item.name}", locks, queues
                )
                for stmt in item.body:
                    v.visit(stmt)
                methods[item.name] = v.info
        out.append(ClassAnalysis(
            module=relmodule, name=node.name,
            lock_attrs=locks, queue_attrs=queues, methods=methods,
        ))
    return out


def _lock_id(cls: ClassAnalysis, dotted: str) -> str:
    """Stable lock identity: module:Class.attr for self locks, else the
    dotted expression itself."""
    last = dotted.rsplit(".", 1)[-1]
    if dotted.startswith("self.") and last in cls.lock_attrs:
        return f"{cls.module}:{cls.name}.{last}"
    return f"{cls.module}:{cls.name}:{dotted}"


def collect_findings(classes: List[ClassAnalysis]) -> List[Finding]:
    findings: List[Finding] = []
    # edge map for inversion detection across the whole scope
    edges: Dict[Tuple[str, str], str] = {}

    # cross-class resolution index: method name → defining scope classes
    defs: Dict[str, List[Tuple[ClassAnalysis, MethodInfo]]] = {}
    for cls in classes:
        for mname, info in cls.methods.items():
            defs.setdefault(mname, []).append((cls, info))

    for cls in classes:
        for mname, info in cls.methods.items():
            # direct blocking calls under a held lock
            for held, call in info.under_lock:
                findings.append(Finding(
                    "LOCK-BLOCKING",
                    f"{cls.module}:{cls.name}.{mname}:"
                    f"{held.rsplit('.', 1)[-1]}:"
                    f"{call.receiver.rsplit('.', 1)[-1]}",
                    f"{cls.module}:{call.lineno}: {cls.name}.{mname} "
                    f"holds {held} while {call.why}",
                ))
            # self-calls under lock into methods that block anywhere
            for held, callee, line in info.self_calls_under_lock:
                target = cls.methods.get(callee)
                if target is None:
                    continue
                if target.blocking and not target.under_lock:
                    why = target.blocking[0].why
                    findings.append(Finding(
                        "LOCK-BLOCKING",
                        f"{cls.module}:{cls.name}.{mname}:"
                        f"{held.rsplit('.', 1)[-1]}:{callee}",
                        f"{cls.module}:{line}: {cls.name}.{mname} holds "
                        f"{held} while calling self.{callee}() which "
                        f"does blocking work ({why})",
                    ))
                # lock edges through the callee
                for acq in target.acquires:
                    a, b = _lock_id(cls, held), _lock_id(cls, acq)
                    if a != b:
                        edges.setdefault(
                            (a, b),
                            f"{cls.module}:{line} "
                            f"({cls.name}.{mname} → self.{callee})",
                        )
            # cross-class propagation: a non-self receiver's method,
            # resolved by name against the scope classes — blocking
            # work in the callee fires at the caller, and the callee's
            # lock acquisitions join the inversion graph. Ambiguous
            # names (several scope classes, disagreeing behavior) are
            # skipped: name resolution is not type inference.
            for held, callee, line, recv in info.ext_calls_under_lock:
                cands = defs.get(callee, [])
                if not cands:
                    continue
                blocking = [
                    c for c in cands
                    if c[1].blocking or c[1].under_lock
                ]
                if len(cands) == 1 or len(blocking) == len(cands):
                    if blocking:
                        tcls, tinfo = blocking[0]
                        why = (
                            tinfo.blocking[0].why if tinfo.blocking
                            else tinfo.under_lock[0][1].why
                        )
                        findings.append(Finding(
                            "LOCK-CROSS-BLOCKING",
                            f"{cls.module}:{cls.name}.{mname}:"
                            f"{held.rsplit('.', 1)[-1]}:{callee}",
                            f"{cls.module}:{line}: {cls.name}.{mname} "
                            f"holds {held} while calling "
                            f"{recv}.{callee}() → {tcls.name}.{callee}"
                            f" which does blocking work ({why})",
                        ))
                if len(cands) == 1:
                    tcls, tinfo = cands[0]
                    for acq in tinfo.acquires:
                        a = _lock_id(cls, held)
                        b = _lock_id(tcls, acq)
                        if a != b:
                            edges.setdefault(
                                (a, b),
                                f"{cls.module}:{line} ({cls.name}."
                                f"{mname} → {tcls.name}.{callee})",
                            )
            # direct nesting edges
            for held, acquired, line in info.edges:
                a, b = _lock_id(cls, held), _lock_id(cls, acquired)
                if a != b:
                    edges.setdefault(
                        (a, b),
                        f"{cls.module}:{line} ({cls.name}.{mname})",
                    )

    # inversions: both A→B and B→A observed anywhere in scope
    reported: Set[Tuple[str, str]] = set()
    for (a, b), where in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in reported:
            reported.add((a, b))
            findings.append(Finding(
                "LOCK-INVERSION",
                f"inversion:{min(a, b)}<->{max(a, b)}",
                f"inconsistent acquisition order: {a} → {b} at {where} "
                f"but {b} → {a} at {edges[(b, a)]} — deadlock-capable",
            ))
    return findings


SCOPE_DIRS = ("cadence_tpu/runtime", "cadence_tpu/checkpoint",
              "cadence_tpu/matching")

# single files outside the scanned packages that grew locks (PR 9's
# telemetry plane: the flight-recorder ring and the registry series
# map) — every serving thread passes through these under load, so
# their locks belong in the same inventory/inversion proof
SCOPE_FILES = ("cadence_tpu/utils/tracing.py",
               "cadence_tpu/utils/metrics.py")


def run(repo_root: str) -> List[Finding]:
    classes: List[ClassAnalysis] = []
    for scope in SCOPE_DIRS:
        base = os.path.join(repo_root, scope)
        if not os.path.isdir(base):
            continue
        for dirpath, _, files in os.walk(base):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                fpath = os.path.join(dirpath, fname)
                rel = os.path.relpath(fpath, repo_root)
                with open(fpath) as f:
                    classes += analyze_module(f.read(), rel)
    for rel in SCOPE_FILES:
        fpath = os.path.join(repo_root, rel)
        if os.path.isfile(fpath):
            with open(fpath) as f:
                classes += analyze_module(f.read(), rel)
    return collect_findings(classes)
