"""Pass 3 — concurrency lint: lock graph + blocking-calls-under-lock.

The runtime is a thread pile: queue pumps, worker pools, ack sweeps,
replication appliers, the shard sequencer. This pass builds, purely
from the AST:

* a **lock inventory** — ``self.x = threading.Lock()/RLock()/
  Condition()`` attributes per class, plus local locks;
* a **lock-order graph** — an edge A→B wherever B is acquired while A
  is held (nested ``with``, ``.acquire()``, or a same-class method call
  that acquires B), with cycle (inversion) detection;
* **blocking-call-under-lock** findings — store I/O (persistence
  managers, sqlite cursors), ``time.sleep``, ``.join()``, blocking
  queue ``get``/``put``, and ``.wait()`` on anything *other than the
  condition being held* (waiting on a held Condition releases it; an
  Event.wait under someone else's lock stalls every other holder).

Cross-class lock propagation: a call under a held lock whose receiver
is NOT ``self`` (``handle.shard.fence()``, ``c.acquire_shards()``) is
resolved by METHOD NAME against every class in scope. When the name
resolves unambiguously — exactly one scope class defines it, or every
defining class agrees it blocks — the callee's blocking work surfaces
as LOCK-CROSS-BLOCKING at the caller, and the callee's lock
acquisitions become cross-class edges in the inversion graph (a
coordinator holding its own lock while fencing a shard context now
participates in the same order proof as the context's lock). Names
defined by many scope classes with disagreeing behavior are skipped —
resolution is by name, not type inference, and a wrong guess would be
noise, not safety.

Known limits (documented, deliberate): remaining cross-class reasoning
is attribute-name heuristics (a call whose receiver chain mentions
``persistence``/store managers counts as I/O), and dynamic dispatch
through callbacks is matched by callable-attribute *name* (e.g.
``self._update_shard_ack(...)``). Non-blocking try-locks
(``acquire(blocking=False)``) are exempt.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding

# threading primitives AND the tracked factory (utils/locks.py):
# sanitizer-instrumented construction sites must stay in the same
# inventory/inversion proof as raw threading ones
LOCK_FACTORIES = {"Lock", "RLock", "Condition",
                  "make_lock", "make_rlock", "make_condition"}

# method names that are store/persistence I/O wherever they appear
STORE_METHODS = {
    "update_shard", "create_shard", "get_shard",
    "append_history_nodes", "read_history_branch", "new_history_branch",
    "get_workflow_execution", "update_workflow_execution",
    "create_workflow_execution", "delete_workflow_execution",
    "get_transfer_tasks", "get_timer_tasks",
    "range_complete_transfer_tasks", "range_complete_timer_tasks",
    "complete_transfer_task", "complete_timer_task",
    "list_domains", "get_domain", "update_domain",
    "put_checkpoint", "list_checkpoints", "list_tree_checkpoints",
    "delete_checkpoint", "prune_tree",
    "execute", "executemany", "executescript", "commit",
}

# receiver-chain substrings that mark a call as store I/O
STORE_RECEIVERS = ("persistence", "_conn", ".store", ".shard.")

ALWAYS_BLOCKING_ATTRS = {"sleep", "join"}

# lock-protocol attrs never treated as cross-class method calls
_LOCK_OPS = {"acquire", "release", "wait", "notify", "notify_all",
             "locked"}

# names shared with builtin container/string/file protocols: a
# same-named scope method is coincidence, not a resolution target
# (``failures.append(...)`` is a list, not TaskWriter.append)
_BUILTIN_METHOD_NAMES = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "get", "put", "add", "discard", "setdefault", "keys",
    "values", "items", "sort", "reverse", "count", "index", "copy",
    "split", "rsplit", "join", "strip", "lstrip", "rstrip", "replace",
    "format", "encode", "decode", "startswith", "endswith", "lower",
    "upper", "read", "write", "close", "flush", "seek",
}

# callable-attribute name fragments treated as blocking when invoked
BLOCKING_CALLABLE_HINTS = ("update_shard",)


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted string of an expression ("self._lock",
    "self.persistence.shard.update_shard", "ctx.lock")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    if isinstance(node, ast.Subscript):
        return f"{_dotted(node.value)}[]"
    return "<expr>"


@dataclasses.dataclass
class BlockingCall:
    receiver: str
    lineno: int
    why: str


def _blocking_reason(
    node: ast.Call, held: Tuple[str, ...], queue_attrs: Set[str]
) -> Optional[str]:
    """Why this call is blocking, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = _dotted(fn.value)
        attr = fn.attr
        if attr in ALWAYS_BLOCKING_ATTRS:
            return f"{recv}.{attr}() blocks"
        if attr == "wait":
            # waiting on the condition you hold releases it; anything
            # else parks the thread with the lock still held
            if recv in held:
                return None
            return f"{recv}.wait() parks the thread while locked"
        if attr == "acquire":
            for kw in node.keywords:
                if (
                    kw.arg == "blocking"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                ):
                    return None
            if recv in held:
                return None  # re-entrant acquire of the held lock
            return None  # plain acquire handled as a lock edge, not I/O
        if attr in ("get", "put") and recv.rsplit(".", 1)[-1] in queue_attrs:
            for kw in node.keywords:
                if kw.arg == "block" and isinstance(
                    kw.value, ast.Constant
                ) and kw.value.value is False:
                    return None
                if kw.arg == "timeout" and isinstance(
                    kw.value, ast.Constant
                ) and kw.value.value == 0:
                    return None
            return f"queue {recv}.{attr}() can block on capacity"
        if attr in STORE_METHODS:
            return f"store I/O {recv}.{attr}(...)"
        if any(s in recv for s in STORE_RECEIVERS):
            # receiver chain names a store manager: any method on it is
            # I/O even if the name isn't in STORE_METHODS
            return f"store I/O {recv}.{attr}(...)"
    elif isinstance(fn, ast.Name):
        pass
    # callable attributes by name: self._update_shard_ack(...)
    if isinstance(fn, ast.Attribute) and any(
        h in fn.attr for h in BLOCKING_CALLABLE_HINTS
    ):
        return f"callable {_dotted(fn)}(...) persists shard state"
    return None


@dataclasses.dataclass
class MethodInfo:
    qualname: str               # Class.method or function name
    acquires: Set[str]          # lock attrs acquired anywhere (self-relative)
    blocking: List[BlockingCall]            # blocking calls ANYWHERE in body
    under_lock: List[Tuple[str, BlockingCall]]   # (held lock, call)
    edges: List[Tuple[str, str, int]]       # (held, acquired, lineno)
    self_calls_under_lock: List[Tuple[str, str, int]]  # (held, method, line)
    # every self.method() call anywhere in the body — the same-class
    # closure that lets edge propagation see a lock acquired two
    # helper hops below the held region
    self_calls: Set[str] = dataclasses.field(default_factory=set)
    # every foreign-receiver method call anywhere in the body (same
    # name filters as ext_calls_under_lock) — the cross-class closure
    # input: ctx.persist() acquiring the shard lock two classes away
    ext_calls: Set[str] = dataclasses.field(default_factory=set)
    # (held lock, method name, lineno, receiver, blocking-classified)
    # for non-self receivers — resolved cross-class by name in
    # collect_findings. Blocking-classified calls still propagate lock
    # EDGES (store I/O acquires the store's lock) but are not
    # re-reported as LOCK-CROSS-BLOCKING (already a LOCK-BLOCKING)
    ext_calls_under_lock: List[
        Tuple[str, str, int, str, bool]
    ] = dataclasses.field(default_factory=list)
    # (held lock, class name, lineno) for ClassName(...) constructor
    # calls under a held lock — construction that leases from the
    # store (TaskListManager) acquires locks the caller must order
    ctor_calls_under_lock: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )
    # every ClassName(...) call anywhere in the body (closure input)
    ctor_calls: Set[str] = dataclasses.field(default_factory=set)


class _MethodVisitor(ast.NodeVisitor):
    def __init__(self, qualname: str, lock_names: Set[str],
                 queue_attrs: Set[str]) -> None:
        self.info = MethodInfo(
            qualname=qualname, acquires=set(), blocking=[],
            under_lock=[], edges=[], self_calls_under_lock=[],
        )
        self.lock_names = lock_names
        self.queue_attrs = queue_attrs
        self.held: List[str] = []

    # -- helpers -------------------------------------------------------

    def _is_known_lock(self, dotted: str) -> bool:
        last = dotted.rsplit(".", 1)[-1]
        return last in self.lock_names or _lockish_name(last)

    def _enter_lock(self, dotted: str, body, lineno: int) -> None:
        if self.held:
            self.info.edges.append((self.held[-1], dotted, lineno))
        self.info.acquires.add(dotted)
        self.held.append(dotted)
        for stmt in body:
            self.visit(stmt)
        self.held.pop()

    # -- visitors ------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        lock_expr = None
        for item in node.items:
            d = _dotted(item.context_expr)
            if self._is_known_lock(d):
                lock_expr = d
                break
        if lock_expr is not None:
            self._enter_lock(lock_expr, node.body, node.lineno)
        else:
            self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        reason = _blocking_reason(node, tuple(self.held), self.queue_attrs)
        if reason is not None:
            call = BlockingCall(
                receiver=_dotted(node.func), lineno=node.lineno, why=reason
            )
            self.info.blocking.append(call)
            if self.held:
                self.info.under_lock.append((self.held[-1], call))
        # blocking .acquire() of another lock while one is held = edge
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "acquire"
        ):
            recv = _dotted(node.func.value)
            nonblocking = any(
                kw.arg == "blocking"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords
            )
            if not nonblocking and self._is_known_lock(recv):
                if self.held and recv != self.held[-1]:
                    self.info.edges.append(
                        (self.held[-1], recv, node.lineno)
                    )
                self.info.acquires.add(recv)
        # self.method(...) under a held lock → propagation candidate
        if (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            self.info.self_calls.add(node.func.attr)
            if self.held:
                self.info.self_calls_under_lock.append(
                    (self.held[-1], node.func.attr, node.lineno)
                )
        # any OTHER receiver's method under a held lock → cross-class
        # propagation candidate (resolved by name in collect_findings);
        # calls already classified blocking above are not re-recorded
        elif (
            isinstance(node.func, ast.Attribute)
            and node.func.attr not in _LOCK_OPS
            and node.func.attr not in _BUILTIN_METHOD_NAMES
        ):
            recv = _dotted(node.func.value)
            if recv != "self" and not recv.startswith("super()"):
                self.info.ext_calls.add(node.func.attr)
                if self.held:
                    self.info.ext_calls_under_lock.append(
                        (self.held[-1], node.func.attr, node.lineno,
                         recv, reason is not None)
                    )
        elif isinstance(node.func, ast.Name) and node.func.id[:1].isupper():
            # ClassName(...) — scope-class construction resolves to
            # __init__ (a constructor that leases from the store
            # acquires the store lock under whatever the caller holds)
            self.info.ctor_calls.add(node.func.id)
            if self.held:
                self.info.ctor_calls_under_lock.append(
                    (self.held[-1], node.func.id, node.lineno)
                )
        self.generic_visit(node)


def _lockish_name(name: str) -> bool:
    """Does this attribute name look like a lock/condition object?"""
    n = name.rsplit(".", 1)[-1]
    return (
        "lock" in n
        or n.lstrip("_") in ("cond", "condition", "cv")
        or n.endswith("_cond")
    )


@dataclasses.dataclass
class ClassAnalysis:
    module: str
    name: str
    lock_attrs: Set[str]
    queue_attrs: Set[str]
    methods: Dict[str, MethodInfo]


def _class_lock_attrs(cls: ast.ClassDef) -> Tuple[Set[str], Set[str]]:
    locks: Set[str] = set()
    queues: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not isinstance(v, ast.Call):
            continue
        fname = (
            v.func.attr if isinstance(v.func, ast.Attribute)
            else v.func.id if isinstance(v.func, ast.Name) else ""
        )
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                if fname in LOCK_FACTORIES:
                    locks.add(tgt.attr)
                elif fname == "Queue":
                    queues.add(tgt.attr)
            elif isinstance(tgt, ast.Name) and fname in LOCK_FACTORIES:
                locks.add(tgt.id)
    return locks, queues


def analyze_module(source: str, relmodule: str) -> List[ClassAnalysis]:
    tree = ast.parse(source)
    out: List[ClassAnalysis] = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        locks, queues = _class_lock_attrs(node)
        methods: Dict[str, MethodInfo] = {}
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                v = _MethodVisitor(
                    f"{node.name}.{item.name}", locks, queues
                )
                for stmt in item.body:
                    v.visit(stmt)
                methods[item.name] = v.info
        out.append(ClassAnalysis(
            module=relmodule, name=node.name,
            lock_attrs=locks, queue_attrs=queues, methods=methods,
        ))
    return out


def _lock_id(cls: ClassAnalysis, dotted: str) -> str:
    """Stable lock identity: module:Class.attr for self locks, else the
    dotted expression itself."""
    last = dotted.rsplit(".", 1)[-1]
    if dotted.startswith("self.") and last in cls.lock_attrs:
        return f"{cls.module}:{cls.name}.{last}"
    return f"{cls.module}:{cls.name}:{dotted}"


def collect_findings(classes: List[ClassAnalysis]) -> List[Finding]:
    findings, _ = collect_graph(classes)
    return findings


def collect_graph(
    classes: List[ClassAnalysis],
) -> Tuple[List[Finding], Dict[Tuple[str, str], str]]:
    """(findings, acquisition-order edge map). The edge map — lock id
    pair → first witnessing site — is the static half of the
    bidirectional lock proof: the runtime witness cross-validates its
    observed edges against it (``testing/race_witness.cross_validate``)
    and ``--emit-lock-graph`` publishes it."""
    findings: List[Finding] = []
    # edge map for inversion detection across the whole scope
    edges: Dict[Tuple[str, str], str] = {}

    # cross-class resolution index: method name → defining scope classes
    defs: Dict[str, List[Tuple[ClassAnalysis, MethodInfo]]] = {}
    by_name: Dict[str, ClassAnalysis] = {}
    for cls in classes:
        by_name.setdefault(cls.name, cls)
        for mname, info in cls.methods.items():
            defs.setdefault(mname, []).append((cls, info))

    # multi-candidate resolution guard: a name defined by several
    # scope classes resolves to ALL of them only when every definer is
    # a persistence-store class (the memory/sqlite manager twins and
    # the checkpoint stores share every verb; either may be behind a
    # store receiver, so edge extraction wants the may-union). Any
    # other collision ("merge" on Histogram vs ReshardCoordinator)
    # stays unresolved — name resolution is not type inference.
    _STORE_MODULES = ("cadence_tpu/runtime/persistence/",
                      "cadence_tpu/checkpoint/")

    def resolve_cands(callee: str) -> List[Tuple[ClassAnalysis, MethodInfo]]:
        cands = defs.get(callee, [])
        if len(cands) <= 1:
            return cands
        if all(
            c[0].module.startswith(_STORE_MODULES) for c in cands
        ):
            return cands
        return []

    # same-class acquisition closure: a callee's lock acquisitions
    # include everything its own self-calls acquire, to any depth —
    # without this, ``with ctx.lock: shard.assign_task_ids(...)`` never
    # produced the ctx.lock → ShardContext._lock edge (assign_task_ids
    # only acquires through next_task_id), and the runtime witness
    # proved the hole by observing edges the static graph lacked
    closure_memo: Dict[Tuple[int, str], Set[Tuple[str, str]]] = {}

    def _eff(
        cls: ClassAnalysis, mname: str,
        stack: Set[Tuple[int, str]],
    ) -> Tuple[Set[Tuple[str, str]], bool]:
        """(closure, tainted). ``tainted`` means a cycle cut truncated
        this computation — such a result is correct for the CURRENT
        root but must not be memoized, or the truncation would leak
        into unrelated callers (a caller of B.n computed while A.m was
        on the stack would permanently miss everything behind A.m)."""
        key = (id(cls), mname)
        hit = closure_memo.get(key)
        if hit is not None:
            return hit, False
        info = cls.methods.get(mname)
        if info is None:
            return set(), False
        if key in stack:
            return {
                (_lock_id(cls, a), f"{cls.name}.{mname}")
                for a in info.acquires
            }, True
        stack.add(key)
        out = {
            (_lock_id(cls, a), f"{cls.name}.{mname}")
            for a in info.acquires
        }
        tainted = False
        for callee in info.self_calls:
            if callee != mname and callee in cls.methods:
                sub, t = _eff(cls, callee, stack)
                out |= sub
                tainted |= t
        for callee in info.ext_calls:
            for tcls, _ in resolve_cands(callee):
                if tcls is not cls:
                    sub, t = _eff(tcls, callee, stack)
                    out |= sub
                    tainted |= t
        for cname in info.ctor_calls:
            tcls = by_name.get(cname)
            if tcls is not None and tcls is not cls:
                sub, t = _eff(tcls, "__init__", stack)
                out |= sub
                tainted |= t
        stack.discard(key)
        if not tainted:
            closure_memo[key] = out
        return out, tainted

    def eff_acquires(
        cls: ClassAnalysis, mname: str,
    ) -> Set[Tuple[str, str]]:
        """Lock IDS transitively acquired by Class.mname: its own
        acquisitions (id-resolved against its class) plus everything
        reachable through same-class self-calls, unambiguously
        resolved foreign-receiver calls, and scope-class constructor
        calls; cycles cut at the recursion point."""
        out, _ = _eff(cls, mname, set())
        return out

    for cls in classes:
        for mname, info in cls.methods.items():
            # direct blocking calls under a held lock
            for held, call in info.under_lock:
                findings.append(Finding(
                    "LOCK-BLOCKING",
                    f"{cls.module}:{cls.name}.{mname}:"
                    f"{held.rsplit('.', 1)[-1]}:"
                    f"{call.receiver.rsplit('.', 1)[-1]}",
                    f"{cls.module}:{call.lineno}: {cls.name}.{mname} "
                    f"holds {held} while {call.why}",
                ))
            # self-calls under lock into methods that block anywhere
            for held, callee, line in info.self_calls_under_lock:
                target = cls.methods.get(callee)
                if target is None:
                    continue
                if target.blocking and not target.under_lock:
                    why = target.blocking[0].why
                    findings.append(Finding(
                        "LOCK-BLOCKING",
                        f"{cls.module}:{cls.name}.{mname}:"
                        f"{held.rsplit('.', 1)[-1]}:{callee}",
                        f"{cls.module}:{line}: {cls.name}.{mname} holds "
                        f"{held} while calling self.{callee}() which "
                        f"does blocking work ({why})",
                    ))
                # lock edges through the callee (call closure)
                a = _lock_id(cls, held)
                for b, via in eff_acquires(cls, callee):
                    if a != b:
                        edges.setdefault(
                            (a, b),
                            f"{cls.module}:{line} "
                            f"({cls.name}.{mname} → self.{callee} "
                            f"[{via}])",
                        )
            # cross-class propagation: a non-self receiver's method,
            # resolved by name against the scope classes — blocking
            # work in the callee fires at the caller, and the callee's
            # lock acquisitions join the inversion graph. Ambiguous
            # names (several scope classes, disagreeing behavior) are
            # skipped: name resolution is not type inference.
            for held, callee, line, recv, blocked in (
                info.ext_calls_under_lock
            ):
                cands = defs.get(callee, [])
                if not cands:
                    continue
                blocking = [
                    c for c in cands
                    if c[1].blocking or c[1].under_lock
                ]
                if not blocked and (
                    len(cands) == 1 or len(blocking) == len(cands)
                ):
                    # already reported as LOCK-BLOCKING when blocked —
                    # the call still propagates edges below
                    if blocking:
                        tcls, tinfo = blocking[0]
                        why = (
                            tinfo.blocking[0].why if tinfo.blocking
                            else tinfo.under_lock[0][1].why
                        )
                        findings.append(Finding(
                            "LOCK-CROSS-BLOCKING",
                            f"{cls.module}:{cls.name}.{mname}:"
                            f"{held.rsplit('.', 1)[-1]}:{callee}",
                            f"{cls.module}:{line}: {cls.name}.{mname} "
                            f"holds {held} while calling "
                            f"{recv}.{callee}() → {tcls.name}.{callee}"
                            f" which does blocking work ({why})",
                        ))
                a = _lock_id(cls, held)
                for tcls, _ in resolve_cands(callee):
                    if tcls is cls:
                        continue
                    for b, via in eff_acquires(tcls, callee):
                        if a != b:
                            edges.setdefault(
                                (a, b),
                                f"{cls.module}:{line} ({cls.name}."
                                f"{mname} → {tcls.name}.{callee} "
                                f"[{via}])",
                            )
            # constructor calls under lock: the constructed class's
            # __init__ closure (a store-leasing constructor acquires
            # the store lock under whatever the caller holds)
            for held, cname, line in info.ctor_calls_under_lock:
                tcls = by_name.get(cname)
                if tcls is None or tcls is cls:
                    continue
                a = _lock_id(cls, held)
                for b, via in eff_acquires(tcls, "__init__"):
                    if a != b:
                        edges.setdefault(
                            (a, b),
                            f"{cls.module}:{line} ({cls.name}.{mname} "
                            f"→ {cname}() [{via}])",
                        )
            # direct nesting edges
            for held, acquired, line in info.edges:
                a, b = _lock_id(cls, held), _lock_id(cls, acquired)
                if a != b:
                    edges.setdefault(
                        (a, b),
                        f"{cls.module}:{line} ({cls.name}.{mname})",
                    )

    # inversions: both A→B and B→A observed anywhere in scope
    reported: Set[Tuple[str, str]] = set()
    for (a, b), where in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in reported:
            reported.add((a, b))
            findings.append(Finding(
                "LOCK-INVERSION",
                f"inversion:{min(a, b)}<->{max(a, b)}",
                f"inconsistent acquisition order: {a} → {b} at {where} "
                f"but {b} → {a} at {edges[(b, a)]} — deadlock-capable",
            ))
    return findings, edges


SCOPE_DIRS = ("cadence_tpu/runtime", "cadence_tpu/checkpoint",
              "cadence_tpu/matching",
              # PR 12: the serving edge — frontend handlers, the
              # routed/retrying clients (stub caches, resolver
              # listeners), and the rpc plane were unscanned lock
              # sites until the runtime witness demanded parity
              "cadence_tpu/frontend", "cadence_tpu/client",
              "cadence_tpu/rpc",
              # PR 14: the resident serving engine's lane-table lock
              "cadence_tpu/serving")

# single files outside the scanned packages that grew locks (PR 9's
# telemetry plane: the flight-recorder ring and the registry series
# map) — every serving thread passes through these under load, so
# their locks belong in the same inventory/inversion proof
SCOPE_FILES = ("cadence_tpu/utils/tracing.py",
               "cadence_tpu/utils/metrics.py")


def scope_classes(repo_root: str) -> List[ClassAnalysis]:
    classes: List[ClassAnalysis] = []
    for scope in SCOPE_DIRS:
        base = os.path.join(repo_root, scope)
        if not os.path.isdir(base):
            continue
        for dirpath, _, files in os.walk(base):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                fpath = os.path.join(dirpath, fname)
                rel = os.path.relpath(fpath, repo_root)
                with open(fpath) as f:
                    classes += analyze_module(f.read(), rel)
    for rel in SCOPE_FILES:
        fpath = os.path.join(repo_root, rel)
        if os.path.isfile(fpath):
            with open(fpath) as f:
                classes += analyze_module(f.read(), rel)
    return classes


def run(repo_root: str) -> List[Finding]:
    return collect_findings(scope_classes(repo_root))


# --------------------------------------------------------------------------
# static graph export + runtime cross-validation support
# --------------------------------------------------------------------------


@dataclasses.dataclass
class LockGraph:
    """The whole static lock picture for one tree: inventory (lock id →
    owning class), acquisition-order edges (id pair → witnessing
    site), and the Pass 3 findings."""

    locks: Dict[str, str]                  # lock id → module:Class
    edges: Dict[Tuple[str, str], str]      # (a, b) → where
    findings: List[Finding]


def build_graph(repo_root: str) -> LockGraph:
    classes = scope_classes(repo_root)
    findings, edges = collect_graph(classes)
    locks: Dict[str, str] = {}
    for cls in classes:
        for attr in sorted(cls.lock_attrs):
            locks[f"{cls.module}:{cls.name}.{attr}"] = (
                f"{cls.module}:{cls.name}"
            )
    return LockGraph(locks=locks, edges=edges, findings=findings)


def _norm_lock_id(lock_id: str) -> Tuple[Optional[str], str]:
    """Normalize a lock id to (Class.attr or None, attr).

    Self-attribute ids ("module:Class.attr") carry the owning class;
    caller-relative expression ids ("module:Class:ctx.lock" — the
    holder nested a FOREIGN receiver's lock, owner class unknowable to
    the AST) normalize to attr only."""
    parts = lock_id.split(":")
    if len(parts) >= 3:
        return None, parts[-1].rsplit(".", 1)[-1].rstrip("[]()")
    tail = parts[-1]
    return tail, tail.rsplit(".", 1)[-1]


def _ends_match(runtime_id: str, static_id: str) -> bool:
    r_ca, r_attr = _norm_lock_id(runtime_id)
    s_ca, s_attr = _norm_lock_id(static_id)
    if s_ca is not None and r_ca is not None:
        return s_ca == r_ca
    return s_attr == r_attr


def edge_in_static(
    runtime_edge: Tuple[str, str],
    static_edges: List[Tuple[str, str]],
) -> bool:
    """Does a runtime-observed edge have a static counterpart?

    Matching is at Class.attr granularity when both sides know the
    owning class, attr granularity when the static endpoint is an
    expression id (the AST saw ``ctx.lock``, not the owner class) —
    the same granularity the static inversion proof itself runs at."""
    a, b = runtime_edge
    return any(
        _ends_match(a, sa) and _ends_match(b, sb)
        for sa, sb in static_edges
    )


# rules whose baselined entries the lock-graph artifact annotates
LOCK_RULES = ("LOCK-BLOCKING", "LOCK-CROSS-BLOCKING", "LOCK-INVERSION")

LOCK_GRAPH_SCHEMA = "lock_graph"
WITNESS_SCHEMA = "lock_witness"


def emit_lock_graph(
    repo_root: str,
    path: str,
    witness_path: Optional[str] = None,
    baseline_path: Optional[str] = None,
    graph: Optional[LockGraph] = None,
) -> Dict:
    """Write the versioned lock-graph artifact: the full static
    inventory + edge list, each edge annotated ``observed``
    true/false against the latest runtime witness
    (``build/lock_witness.json``, written by the sanitized tier-1 /
    ``CHAOS_SANITIZE=1`` runs), and every baselined lock finding
    annotated ``observed``/``never-observed`` — turning the
    baseline's prose justifications into machine-checked evidence.

    With no witness artifact on disk the annotations are ``null`` and
    ``witness`` records why — the static half still publishes.
    ``graph`` takes a prebuilt :class:`LockGraph` so a gate that
    already ran the static pass does not re-parse the tree."""
    import fnmatch as _fnmatch
    import json

    from . import artifact
    from .findings import Baseline

    if graph is None:
        graph = build_graph(repo_root)

    witness = None
    witness_note = "no witness artifact (run a sanitized suite first)"
    wpath = witness_path or os.path.join(
        repo_root, "build", "lock_witness.json"
    )
    if os.path.isfile(wpath):
        try:
            witness = artifact.load_artifact(wpath, WITNESS_SCHEMA)
            witness_note = wpath
        except (ValueError, json.JSONDecodeError) as e:
            witness_note = f"witness artifact rejected: {e}"

    observed_edges = []
    blocking_anchors: List[str] = []
    inversion_anchors: List[str] = []
    if witness is not None:
        observed_edges = [(e["a"], e["b"]) for e in witness["edges"]]
        blocking_anchors = [
            b["anchor"] for b in witness.get("blocking", [])
        ]
        inversion_anchors = [
            f["anchor"] for f in witness.get("findings", [])
            if f["rule"] == "RUNTIME-LOCK-INVERSION"
        ]

    def _edge_observed(a: str, b: str):
        if witness is None:
            return None
        return any(
            _ends_match(ra, a) and _ends_match(rb, b)
            for ra, rb in observed_edges
        )

    def _entry_observed(rule: str, anchor: str):
        if witness is None:
            return None
        if rule == "LOCK-INVERSION":
            # runtime inversion anchors carry a "runtime-" prefix on
            # top of the static "inversion:..." shape — strip it so a
            # baselined static inversion can actually match
            pool = [
                a[len("runtime-"):] if a.startswith("runtime-") else a
                for a in inversion_anchors
            ]
        else:
            pool = blocking_anchors
        return any(
            _fnmatch.fnmatchcase(runtime_anchor, anchor)
            for runtime_anchor in pool
        )

    baseline = Baseline()
    bpath = baseline_path or os.path.join(
        repo_root, "config", "lint_baseline.json"
    )
    if os.path.isfile(bpath):
        baseline = Baseline.load(bpath)

    lock_findings = [f for f in graph.findings if f.rule in LOCK_RULES]
    entries = []
    for e in baseline.entries:
        if e.rule not in LOCK_RULES:
            continue
        obs = _entry_observed(e.rule, e.anchor)
        entries.append({
            "rule": e.rule,
            "anchor": e.anchor,
            "justification": e.justification,
            "matches_static": sum(
                1 for f in lock_findings if e.matches(f)
            ),
            "observed": obs,
            "status": (
                "unknown" if obs is None
                else "observed" if obs else "never-observed"
            ),
        })

    runtime_only = []
    if witness is not None:
        static_edge_keys = list(graph.edges)
        runtime_only = [
            {"a": a, "b": b}
            for a, b in observed_edges
            if not edge_in_static((a, b), static_edge_keys)
        ]

    doc = {
        "locks": [
            {"id": lock_id, "owner": owner}
            for lock_id, owner in sorted(graph.locks.items())
        ],
        "edges": [
            {
                "a": a, "b": b, "where": where,
                "observed": _edge_observed(a, b),
            }
            for (a, b), where in sorted(graph.edges.items())
        ],
        "findings": [
            {"rule": f.rule, "anchor": f.anchor}
            for f in lock_findings
        ],
        "baseline_entries": entries,
        "runtime_only_edges": runtime_only,
        "witness": witness_note,
    }
    artifact.write_artifact(path, LOCK_GRAPH_SCHEMA, doc)
    return doc
