"""AST extraction of the host oracle's transition table.

Three extractors, all purely syntactic (no imports of the target
modules, so a broken tree still lints):

* :func:`extract_event_dispatch` — the ``et == EventType.X`` /
  ``et in (EventType.A, ...)`` if/elif chain of a function body
  (``StateBuilder.apply_events`` here, reused for ``pack_workflow``),
  returning {event-type name → branch info (handler calls, is_noop)}.
* :func:`extract_replicate_writes` — per ``MutableState.replicate_*``
  method: which pending-map tables it touches and which
  ``execution_info`` fields it assigns, with a same-class call closure
  so ``replicate_decision_task_completed_event → _delete_decision``
  attributes the delete's writes to the replicate method.
* :func:`extract_rel_ts_attrs` — which ``attrs[i]`` slots
  ``pack_workflow`` fills from ``rel_ts(...)`` per event type: those
  event columns carry epoch-relative timestamps onto the device, so
  any state column derived from them is epoch-bearing.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

# oracle pending-map attribute → schema table name
PENDING_TABLES = {
    "pending_activities": "activities",
    "pending_timers": "timers",
    "pending_children": "children",
    "pending_request_cancels": "cancels",
    "pending_signals": "signals",
}


@dataclasses.dataclass
class Branch:
    """One arm of the event-type dispatch chain."""

    types: Tuple[str, ...]          # EventType member names
    handler_calls: Tuple[str, ...]  # ms.replicate_* method names called
    is_noop: bool                   # body is (effectively) `pass`


def _event_types_of(test: ast.expr) -> Optional[Tuple[str, ...]]:
    """EventType names matched by an if/elif test, or None if the test
    isn't an event-type dispatch."""

    def name_of(node: ast.expr) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("EventType", "E")
        ):
            return node.attr
        return None

    if not isinstance(test, ast.Compare) or len(test.ops) != 1:
        return None
    op = test.ops[0]
    rhs = test.comparators[0]
    if isinstance(op, ast.Eq):
        n = name_of(rhs)
        return (n,) if n else None
    if isinstance(op, ast.In) and isinstance(rhs, (ast.Tuple, ast.List)):
        names = [name_of(e) for e in rhs.elts]
        if all(names):
            return tuple(names)
    return None


def _calls_on(body: List[ast.stmt], receiver: str, prefix: str) -> List[str]:
    out: List[str] = []
    for node in ast.walk(ast.Module(body=body, type_ignores=[])):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == receiver
            and node.func.attr.startswith(prefix)
        ):
            out.append(node.func.attr)
    return out


def extract_event_dispatch(
    source: str,
    func_name: str = "apply_events",
    receiver: str = "ms",
    call_prefix: str = "replicate_",
) -> Dict[str, Branch]:
    """Parse the event-type dispatch chain of ``func_name``.

    Returns {EventType name → Branch}. Types not present raise-by-default
    in the oracle (``else: raise``) and are simply absent here.
    """
    tree = ast.parse(source)
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {func_name!r} in source")

    table: Dict[str, Branch] = {}

    def walk_chain(stmt: ast.If) -> None:
        cur: Optional[ast.stmt] = stmt
        while isinstance(cur, ast.If):
            types = _event_types_of(cur.test)
            if types is not None:
                calls = tuple(_calls_on(cur.body, receiver, call_prefix))
                is_noop = not calls and all(
                    isinstance(s, (ast.Pass, ast.Expr)) for s in cur.body
                )
                for t in types:
                    table[t] = Branch(types, calls, is_noop)
            nxt = cur.orelse
            cur = nxt[0] if len(nxt) == 1 else None

    for node in ast.walk(fn):
        if isinstance(node, ast.If) and _event_types_of(node.test) is not None:
            # only take top chains (an elif arm is reachable from its
            # parent's orelse; walking it again is harmless — same data)
            walk_chain(node)
    return table


# --------------------------------------------------------------------------
# MutableState replicate-method write sets
# --------------------------------------------------------------------------


@dataclasses.dataclass
class WriteSet:
    tables: Set[str] = dataclasses.field(default_factory=set)
    exec_fields: Set[str] = dataclasses.field(default_factory=set)


def _method_writes(fn: ast.FunctionDef) -> Tuple[WriteSet, Set[str]]:
    """Direct writes of one method + names of self-methods it calls."""
    ws = WriteSet()
    calls: Set[str] = set()
    # aliases of self.execution_info within the method (ei = self.execution_info)
    exec_aliases: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Attribute
        ):
            v = node.value
            if (
                isinstance(v.value, ast.Name)
                and v.value.id == "self"
                and v.attr == "execution_info"
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        exec_aliases.add(tgt.id)
    for node in ast.walk(fn):
        # pending-map touches (read/write/del all count as "touches")
        if isinstance(node, ast.Attribute) and node.attr in PENDING_TABLES:
            ws.tables.add(PENDING_TABLES[node.attr])
        # execution_info field stores: self.execution_info.f = / ei.f =
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                if not isinstance(tgt, ast.Attribute):
                    continue
                base = tgt.value
                if (
                    isinstance(base, ast.Attribute)
                    and base.attr == "execution_info"
                ) or (
                    isinstance(base, ast.Name) and base.id in exec_aliases
                ):
                    ws.exec_fields.add(tgt.attr)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            calls.add(node.func.attr)
    return ws, calls


def extract_replicate_writes(
    source: str, class_name: str = "MutableState"
) -> Dict[str, WriteSet]:
    """Per-method write sets for ``class_name``, with writes of called
    same-class methods folded in (fixpoint)."""
    tree = ast.parse(source)
    cls = None
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            cls = node
            break
    if cls is None:
        raise ValueError(f"no class {class_name!r} in source")
    direct: Dict[str, WriteSet] = {}
    callees: Dict[str, Set[str]] = {}
    for item in cls.body:
        if isinstance(item, ast.FunctionDef):
            ws, calls = _method_writes(item)
            direct[item.name] = ws
            callees[item.name] = calls

    # fixpoint: fold callee writes into callers
    changed = True
    while changed:
        changed = False
        for m, calls in callees.items():
            ws = direct[m]
            for c in calls:
                if c not in direct:
                    continue
                cw = direct[c]
                if not (cw.tables <= ws.tables) or not (
                    cw.exec_fields <= ws.exec_fields
                ):
                    ws.tables |= cw.tables
                    ws.exec_fields |= cw.exec_fields
                    changed = True
    return direct


# --------------------------------------------------------------------------
# pack_workflow rel_ts attribute slots
# --------------------------------------------------------------------------


def extract_rel_ts_attrs(
    source: str, func_name: str = "pack_workflow"
) -> Dict[str, Set[int]]:
    """{EventType name → attr indices assigned from rel_ts(...)}.

    An ``attrs[i] = ... rel_ts(...) ...`` under an event-type branch
    marks EV_A{i} as epoch-bearing for that type.
    """
    tree = ast.parse(source)
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {func_name!r} in source")

    out: Dict[str, Set[int]] = {}

    def has_rel_ts(node: ast.expr) -> bool:
        for n in ast.walk(node):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "rel_ts"
            ):
                return True
        return False

    def scan_branch(types: Tuple[str, ...], body: List[ast.stmt]) -> None:
        for node in ast.walk(ast.Module(body=body, type_ignores=[])):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "attrs"
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, int)
                    and has_rel_ts(node.value)
                ):
                    for t in types:
                        out.setdefault(t, set()).add(tgt.slice.value)

    def walk_chain(stmt: ast.If) -> None:
        cur: Optional[ast.stmt] = stmt
        while isinstance(cur, ast.If):
            types = _event_types_of(cur.test)
            if types is not None:
                scan_branch(types, cur.body)
            nxt = cur.orelse
            cur = nxt[0] if len(nxt) == 1 else None

    for node in ast.walk(fn):
        if isinstance(node, ast.If) and _event_types_of(node.test) is not None:
            walk_chain(node)
    return out


def extract_attr_indices(
    source: str, func_name: str = "pack_workflow"
) -> Set[int]:
    """Every ``attrs[i]`` store index in ``func_name`` — checked against
    the schema's EV_A0..EV_A7 window (an out-of-window write would be
    silently dropped by the row constructor or corrupt a neighbor)."""
    tree = ast.parse(source)
    fn = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            fn = node
            break
    if fn is None:
        raise ValueError(f"no function {func_name!r} in source")
    out: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "attrs"
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, int)
                ):
                    out.add(tgt.slice.value)
    return out
