"""Pass 4: metric-name declaration check (rule METRIC-UNDECLARED).

Generalizes the replication metrics source-scan test (PR 8,
tests/test_replication_transport.py) into a lint-gate rule over the
whole runtime: every literal metric name emitted via
``.inc("...")`` / ``.gauge("...")`` / ``.record("...")`` under the
scanned packages must appear in one of the ``*_METRICS`` catalogs in
utils/metrics_defs.py (or be one of the standard per-operation triple
names). The catalogs are the operator documentation — dashboards,
alerts and the README glossary are written against them — so an
undeclared emission is a silently undocumented signal.

Scope and mechanics:

* scanned packages: ``cadence_tpu/runtime``, ``cadence_tpu/ops``,
  ``cadence_tpu/matching``, ``cadence_tpu/checkpoint`` (the emission
  surfaces; utils/ emits only its own self-telemetry, covered by the
  TELEMETRY tuple's coverage test);
* only **constant-string** first arguments fire — f-strings and
  variables (the persistence decorator's per-API ``{name}.latency``
  family) are dynamic names outside the catalog contract and are
  skipped;
* anchors are ``<relpath>:<metric_name>`` — stable under unrelated
  edits, one finding per (file, name) after dedupe.

The inverse direction (declared but never emitted) stays with the
per-family coverage tests, which can assert it precisely.
"""

from __future__ import annotations

import ast
import os
from typing import List, Sequence, Set

from .findings import Finding

RULE = "METRIC-UNDECLARED"

SCAN_DIRS: Sequence[str] = (
    "cadence_tpu/runtime",
    "cadence_tpu/ops",
    "cadence_tpu/matching",
    "cadence_tpu/checkpoint",
    "cadence_tpu/serving",
)

EMIT_METHODS = frozenset({"inc", "gauge", "record"})


def declared_names() -> Set[str]:
    """The union of every ``*_METRICS`` tuple in utils/metrics_defs.py
    plus the standard triple and the registry's own overflow counter —
    the full catalog an emission may legally use."""
    from cadence_tpu.utils import metrics_defs as defs
    from cadence_tpu.utils.metrics import DROPPED_SERIES

    names: Set[str] = set()
    for attr in dir(defs):
        if attr.endswith("_METRICS"):
            value = getattr(defs, attr)
            if isinstance(value, tuple) and all(
                isinstance(v, str) for v in value
            ):
                names.update(value)
    names.update({defs.REQUESTS, defs.LATENCY, defs.ERRORS})
    names.add(DROPPED_SERIES)
    return names


def scan_source(
    src: str, relpath: str, declared: Set[str]
) -> List[Finding]:
    """Findings for every undeclared constant-string metric emission in
    one module's source (exposed separately so the known-bad fixture
    tests can feed synthetic modules)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(
            rule=RULE,
            anchor=f"{relpath}:<syntax-error>",
            message=f"{relpath}: unparseable source ({e})",
        )]
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in EMIT_METHODS:
            continue
        arg = node.args[0]
        if not isinstance(arg, ast.Constant) or not isinstance(
            arg.value, str
        ):
            continue  # dynamic name: outside the catalog contract
        name = arg.value
        if name in declared:
            continue
        out.append(Finding(
            rule=RULE,
            anchor=f"{relpath}:{name}",
            message=(
                f"{relpath}:{node.lineno}: metric '{name}' is emitted "
                f"via .{fn.attr}() but declared in no "
                "utils/metrics_defs.py *_METRICS catalog — declare it "
                "(with operator docs) or rename to a declared family"
            ),
        ))
    return out


def run(repo_root: str) -> List[Finding]:
    declared = declared_names()
    findings: List[Finding] = []
    for scan_dir in SCAN_DIRS:
        base = os.path.join(repo_root, scan_dir)
        if not os.path.isdir(base):
            continue
        for dirpath, _, files in os.walk(base):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                fpath = os.path.join(dirpath, fname)
                rel = os.path.relpath(fpath, repo_root)
                with open(fpath) as f:
                    findings.extend(scan_source(f.read(), rel, declared))
    return findings
