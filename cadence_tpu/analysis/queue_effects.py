"""Pass 5 — queue-task effect analysis (the parallel-queue proof).

Extends the PR-4 discipline (event-type × state-column write matrix for
the replay kernel) one layer up, to the queue-task handlers that today
run strictly sequentially per shard. For every handler reachable from a
queue dispatch table — ``TransferQueueProcessor._process_*``,
``TimerQueueProcessor._process_*``, the standby verification twins, and
the NDC replication apply path — this pass AST-derives the handler's
*effect footprint*:

* persistence **surfaces** read/written (execution rows, current-run
  rows, history branches, queue-task rows, matching task lists,
  visibility records, checkpoints — the vocabulary lives in
  ``runtime/queues/effects.py`` so the runtime witness shares it);
* **mutable-state columns** read/written (``execution_info`` fields +
  pending-map tables, reusing oracle_ast.py's alias/write-set
  machinery);
* **cross-workflow effects** (parent-close-policy fan-out, child
  starts, external cancel/signal) — the effects that break
  per-workflow conflict keying.

and diffs it against the declared footprint table
(``runtime/queues/effects.TASK_FOOTPRINTS``):

| rule | fires when |
|---|---|
| ``QUEUE-EFFECT-UNKNOWN`` | the footprint is unextractable: a call on an effect-carrying receiver (persistence/engine/matching/…) with no vocabulary entry, an untracked bare helper, or dynamic dispatch inside a handler body |
| ``QUEUE-CONFLICT-UNDECLARED`` | the handler touches a persistence surface outside its declared footprint (or has no declaration at all) |
| ``QUEUE-CROSS-WF`` | the handler fans out to another workflow without declaring the effect |

Extraction is purely syntactic over handler bodies with a same-class
call closure (``self._helper`` folds the helper's effects into the
caller, fixpoint) plus a small vocabulary of module-level helpers
(``delete_workflow_retention``, ``open_visibility_record``). Calls on
receivers with no effect-carrying name (in-memory version-history
algebra, record constructors, logging) default to neutral — the
deliberately conservative half the runtime *effect witness*
(testing/effect_witness.py) covers dynamically: recorded persistence
calls must land inside the static footprint, so a neutral-defaulted
call that actually hits the store fails the chaos witness test.

The footprints also feed ``--emit-conflict-matrix``: the task-type ×
task-type commute/conflict matrix (runtime/queues/effects.py
``build_conflict_matrix``) written as a versioned JSON artifact — the
future parallel-queue executor's gate.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding
from .oracle_ast import PENDING_TABLES

RULE_UNKNOWN = "QUEUE-EFFECT-UNKNOWN"
RULE_UNDECLARED = "QUEUE-CONFLICT-UNDECLARED"
RULE_CROSS = "QUEUE-CROSS-WF"

# (module path, class, plane, task-type enum receiver)
DISPATCH_CLASSES = (
    ("cadence_tpu/runtime/queues/transfer.py",
     "TransferQueueProcessor", "transfer", "TransferTaskType"),
    ("cadence_tpu/runtime/queues/timer.py",
     "TimerQueueProcessor", "timer", "TimerTaskType"),
    ("cadence_tpu/runtime/queues/standby.py",
     "TransferQueueStandbyProcessor", "transfer-standby",
     "TransferTaskType"),
    ("cadence_tpu/runtime/queues/standby.py",
     "TimerQueueStandbyProcessor", "timer-standby", "TimerTaskType"),
)

# the NDC apply path is not task-type dispatched; its entry points are
# pseudo task types on the "replication" plane
REPLICATION_HANDLERS = (
    ("cadence_tpu/runtime/replication/ndc.py", "NDCHistoryReplicator",
     "replication", {
         "apply_events": "HistoryReplication",
         "apply_state_snapshot": "SnapshotReplication",
         "backfill_history": "HistoryBackfill",
     }),
)

# ---------------------------------------------------------------------------
# call vocabulary
# ---------------------------------------------------------------------------

# receiver-chain fragments that mark a receiver as effect-carrying: a
# call on one of these MUST classify (vocabulary or neutral list) or it
# is an unextractable footprint (QUEUE-EFFECT-UNKNOWN)
EFFECT_RECEIVER_HINTS = (
    "persistence", "engine", "matching", "visibility", "history_client",
    "shard", "txn", "ctx", "store", "rebuilder", "client",
)

# cross-workflow client verbs → (xwf effect, implied surface writes on
# the TARGET workflow). The implied writes ride in the footprint so the
# runtime witness can attribute the in-process fan-out's persistence
# calls to the originating task.
XWF_CLIENT_VERBS = {
    "record_child_execution_completed": (
        "xwf.record_child_close",
        ("execution", "history", "queue_tasks", "shard_seq"),
    ),
    "terminate_workflow_execution": (
        "xwf.terminate",
        ("execution", "history", "queue_tasks", "shard_seq"),
    ),
    "request_cancel_workflow_execution": (
        "xwf.request_cancel",
        ("execution", "history", "queue_tasks", "shard_seq"),
    ),
    "signal_workflow_execution": (
        "xwf.signal",
        ("execution", "history", "queue_tasks", "shard_seq"),
    ),
    "start_workflow_execution": (
        "xwf.start_child",
        ("execution", "current_run", "history", "queue_tasks",
         "shard_seq", "task_store", "visibility"),
    ),
}

# engine verbs that mint events on the task's OWN workflow
ENGINE_MINT_VERBS = {
    "record_external_cancel_result", "record_external_signal_result",
    "record_child_execution_started", "record_start_child_execution_failed",
}

# neutral methods allowed on effect-carrying receivers (reads of
# in-memory state, notifier wakes, span/cache plumbing)
NEUTRAL_EFFECT_METHODS = {
    "now", "current_time", "tagged", "evict", "get_or_create",
    "notify", "_notify", "close",  # txn.close handled explicitly below
}

# bare module-level helper functions with known effects
FUNC_EFFECTS = {
    "delete_workflow_retention": {
        "reads": {"execution"},
        "writes": {"execution", "current_run", "visibility", "history"},
    },
    "open_visibility_record": {"reads": set(), "writes": set()},
    "try_continue_after_close": {
        # cron/retry relaunch: mints the continue/close events via the
        # caller's txn and reads the first event for the relaunch attrs
        "reads": {"history"},
        "writes": {"execution", "history", "queue_tasks"},
    },
}

# neutral bare callables (builtins + pure in-module helpers)
NEUTRAL_FUNCS = {
    "dict", "list", "set", "tuple", "frozenset", "sorted", "max", "min",
    "len", "int", "str", "float", "bool", "enumerate", "zip", "range",
    "repr", "isinstance", "getattr", "setattr", "hasattr", "print",
    "abs", "sum", "any", "all", "iter", "next", "vars", "type",
    "task_span", "make_fault_hook", "defer_task", "read_due_timers",
    "run_task_attempts", "sweep_ack", "timed_task", "refresh_tasks",
    "task_effect_scope", "_incoming_history",
}

_LOG_RECEIVERS = {"_log", "_tlog", "_slog", "_gclog", "log", "logger"}
_LOG_METHODS = {"info", "debug", "warning", "error", "exception"}


def _dotted(node: ast.expr) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_dotted(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_dotted(node.func)}()"
    if isinstance(node, ast.Subscript):
        return f"{_dotted(node.value)}[]"
    return "<expr>"


@dataclasses.dataclass
class ExtractedFootprint:
    """AST-derived effect footprint of one handler closure."""

    reads: Set[str] = dataclasses.field(default_factory=set)
    writes: Set[str] = dataclasses.field(default_factory=set)
    cross_workflow: Set[str] = dataclasses.field(default_factory=set)
    ms_reads: Set[str] = dataclasses.field(default_factory=set)
    ms_writes: Set[str] = dataclasses.field(default_factory=set)
    unknown: List[Tuple[int, str]] = dataclasses.field(default_factory=list)
    self_calls: Set[str] = dataclasses.field(default_factory=set)

    def merge(self, other: "ExtractedFootprint") -> bool:
        """Fold ``other`` (a callee) into this footprint; True when
        anything new arrived (drives the closure fixpoint)."""
        before = (
            len(self.reads), len(self.writes), len(self.cross_workflow),
            len(self.ms_reads), len(self.ms_writes), len(self.unknown),
        )
        self.reads |= other.reads
        self.writes |= other.writes
        self.cross_workflow |= other.cross_workflow
        self.ms_reads |= other.ms_reads
        self.ms_writes |= other.ms_writes
        for u in other.unknown:
            if u not in self.unknown:
                self.unknown.append(u)
        after = (
            len(self.reads), len(self.writes), len(self.cross_workflow),
            len(self.ms_reads), len(self.ms_writes), len(self.unknown),
        )
        return after != before


class _EffectVisitor(ast.NodeVisitor):
    """Classify every call in one method body into surface effects.

    ``class_methods`` drives the same-class closure (self-calls are
    recorded, resolved by the caller's fixpoint); ``module_funcs`` are
    functions defined in the same module (treated like FUNC_EFFECTS
    entries when present, neutral otherwise — a module helper the
    vocabulary doesn't know is exactly the "untracked helper" case and
    fires UNKNOWN)."""

    def __init__(self, class_methods: Set[str], module_funcs: Set[str],
                 local_names: Set[str] = frozenset()) -> None:
        self.fp = ExtractedFootprint()
        self.class_methods = class_methods
        self.module_funcs = module_funcs
        # parameters + nested defs + lambda bindings of THIS method: a
        # call through one is a locally-visible callable whose body is
        # visited where it is defined (nested def) or bound (argument
        # at the call site) — neutral here, never an untracked helper
        self.local_names = set(local_names)
        # Name → persistence manager, for `history = self.shard.
        # persistence.history` style aliases
        self.mgr_aliases: Dict[str, str] = {}
        # names bound to a whole persistence BUNDLE (`p = self.shard.
        # persistence`): calls through `p.<manager>.<method>` classify
        # by the manager segment
        self.bundle_aliases: Set[str] = set()
        # Names bound to execution_info within the body (pending-map
        # tables are matched by attribute name, receiver-independent)
        self.ei_aliases: Set[str] = {"ei"}

    # -- helpers -------------------------------------------------------

    def _surface(self, surface: str, kind: str) -> None:
        (self.fp.reads if kind == "r" else self.fp.writes).add(surface)

    def _manager_effect(self, manager: str, method: str) -> None:
        from cadence_tpu.runtime.queues import effects as rt

        for surface, kind in rt.verb_effects(manager, method):
            self._surface(surface, kind)

    def _unknown(self, node: ast.Call, why: str) -> None:
        self.fp.unknown.append((node.lineno, why))

    # -- alias discovery ----------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        chain = _dotted(node.value) if isinstance(
            node.value, (ast.Attribute, ast.Name)
        ) else ""
        segs = chain.replace("()", "").split(".") if chain else []
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if segs and segs[-1].endswith("persistence"):
                self.bundle_aliases.add(tgt.id)
            elif any(s.endswith("persistence") for s in segs[:-1]):
                self.mgr_aliases[tgt.id] = segs[-1]
            elif chain.endswith(".execution_info"):
                self.ei_aliases.add(tgt.id)
        # ms column writes: ei.field = / ms.execution_info.field =
        for tgt in node.targets:
            self._ms_store(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._ms_store(node.target)
        self.generic_visit(node)

    def _ms_store(self, tgt: ast.expr) -> None:
        if not isinstance(tgt, ast.Attribute):
            return
        base = tgt.value
        if isinstance(base, ast.Name) and base.id in self.ei_aliases:
            self.fp.ms_writes.add(f"exec:{tgt.attr}")
        elif (
            isinstance(base, ast.Attribute)
            and base.attr == "execution_info"
        ):
            self.fp.ms_writes.add(f"exec:{tgt.attr}")

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # ms column/table reads (loads only; stores recorded above)
        if isinstance(node.ctx, ast.Load):
            base = node.value
            if isinstance(base, ast.Name) and base.id in self.ei_aliases:
                self.fp.ms_reads.add(f"exec:{node.attr}")
            if node.attr in PENDING_TABLES:
                self.fp.ms_reads.add(PENDING_TABLES[node.attr])
        self.generic_visit(node)

    # -- call classification ------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        self._classify(node)
        self.generic_visit(node)

    def _classify(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in FUNC_EFFECTS:
                eff = FUNC_EFFECTS[name]
                self.fp.reads |= eff["reads"]
                self.fp.writes |= eff["writes"]
                return
            if (
                name in NEUTRAL_FUNCS
                or name in self.module_funcs
                or name in self.local_names
            ):
                return
            if name[:1].isupper():
                return  # constructor / exception: in-memory
            self._unknown(node, f"untracked helper {name}()")
            return
        if not isinstance(fn, ast.Attribute):
            # calling a subscript/lambda result: dynamic dispatch
            self._unknown(node, f"dynamic call {_dotted(fn)}(...)")
            return

        recv = _dotted(fn.value)
        tail = recv.rsplit(".", 1)[-1]
        attr = fn.attr

        if tail in _LOG_RECEIVERS and attr in _LOG_METHODS:
            return
        # persistence chains + aliases of them: any segment NAMING the
        # bundle ("persistence", "get_persistence()", …) classifies the
        # next segment as the manager — a bundle reached through a
        # helper call must not fall through to neutral
        segs = recv.replace("()", "").split(".")
        for i, seg in enumerate(segs[:-1]):
            if seg.endswith("persistence"):
                self._manager_effect(segs[i + 1], attr)
                return
        if isinstance(fn.value, ast.Name) and fn.value.id in self.mgr_aliases:
            self._manager_effect(self.mgr_aliases[fn.value.id], attr)
            return
        parts = recv.split(".")
        if parts[0] in self.bundle_aliases and len(parts) >= 2:
            self._manager_effect(parts[1], attr)
            return
        # checkpoint store handle (mgr.store.put_checkpoint)
        if tail == "store" and "checkpoint" in attr:
            self._surface("checkpoint",
                          "r" if attr.startswith(("get_", "list_")) else "w")
            return
        # matching pushes
        if tail == "matching" and attr.startswith("add_"):
            self._surface("task_store", "w")
            return
        # visibility records
        if tail == "visibility":
            self._surface(
                "visibility",
                "r" if attr.startswith(("get_", "list_", "count_")) else "w",
            )
            return
        # cross-workflow client calls
        if tail == "history_client":
            if attr in XWF_CLIENT_VERBS:
                xwf, implied = XWF_CLIENT_VERBS[attr]
                self.fp.cross_workflow.add(xwf)
                self.fp.writes |= set(implied)
                return
            self._unknown(node, f"history_client.{attr}(...) unvocabularied")
            return
        # engine surface
        if "engine" in recv.split("."):
            if "domains" in recv.split("."):
                self._surface("metadata", "r")
                return
            if attr == "with_workflow":
                self._surface("execution", "r")
                return
            if attr in ENGINE_MINT_VERBS:
                for s in ("execution", "history", "queue_tasks",
                          "shard_seq"):
                    self._surface(s, "w")
                return
            if attr in ("_txn",) or attr in NEUTRAL_EFFECT_METHODS:
                return
            if attr in ("cache",):
                return
            self._unknown(node, f"engine.{attr}(...) unvocabularied")
            return
        # active-transaction mints (inside _mutate-style closures)
        if tail == "txn":
            if attr.startswith("add_"):
                for s in ("execution", "history", "queue_tasks"):
                    self._surface(s, "w")
                return
            if attr == "schedule_timer_task":
                self._surface("queue_tasks", "w")
                return
            if attr == "close":
                for s in ("execution", "history", "queue_tasks",
                          "shard_seq"):
                    self._surface(s, "w")
                return
            if attr.startswith(("has_", "is_", "get_")):
                return
            self._unknown(node, f"txn.{attr}(...) unvocabularied")
            return
        # workflow execution context
        if tail == "ctx":
            if attr == "load":
                self._surface("execution", "r")
                return
            if attr == "update_workflow":
                for s in ("execution", "history", "queue_tasks",
                          "shard_seq"):
                    self._surface(s, "w")
                return
            if attr in ("read_history", "get_event"):
                self._surface("history", "r")
                return
            self._unknown(node, f"ctx.{attr}(...) unvocabularied")
            return
        # shard context
        if tail == "shard":
            if attr in ("now",):
                return
            if attr in ("next_task_id", "assign_task_ids"):
                self._surface("shard_seq", "w")
                if attr == "assign_task_ids":
                    self._surface("queue_tasks", "w")
                return
            self._unknown(node, f"shard.{attr}(...) unvocabularied")
            return
        # rebuilder: reads history (+checkpoint consult/refresh)
        if tail in ("rebuilder", "rb") or recv.endswith(".rebuilder"):
            if attr in ("rebuild", "rebuild_many"):
                self._surface("history", "r")
                self._surface("checkpoint", "r")
                self._surface("checkpoint", "w")
                return
            self._unknown(node, f"rebuilder.{attr}(...) unvocabularied")
            return
        # archival fan-out
        if attr == "maybe_archive":
            self._surface("archival", "w")
            return
        # state-builder apply: in-memory mutable-state mutation (the
        # persisted write is the explicit update/create call)
        if tail == "sb" and attr == "apply_events":
            self.fp.ms_writes.add("state_builder")
            return
        # domain cache off a bare name (self.domains.resolve)
        if tail == "domains":
            self._surface("metadata", "r")
            return
        # allocator classification reads domain records
        if tail == "_allocator":
            self._surface("metadata", "r")
            return
        # self-calls: same-class closure, resolved by the caller
        if isinstance(fn.value, ast.Name) and fn.value.id == "self":
            if attr in self.class_methods:
                self.fp.self_calls.add(attr)
                return
            if attr in ("_task_notifier", "_timer_notifier",
                        "_fault_hook", "_on_handover"):
                return  # pump wakes / chaos hooks: no persistence
            if attr == "_is_active_locally":
                # constructor-injected active-cluster predicate: a
                # domain-record read however it is wired
                self._surface("metadata", "r")
                return
            self._unknown(node, f"self.{attr}(...) not a class method")
            return
        # any other effect-carrying receiver: must classify
        if any(h in recv.split(".") for h in EFFECT_RECEIVER_HINTS):
            if attr in NEUTRAL_EFFECT_METHODS:
                return
            self._unknown(node, f"{recv}.{attr}(...) unvocabularied")
            return
        # everything else (version-history algebra, record objects,
        # containers) is in-memory: neutral by default — the runtime
        # effect witness covers this conservative half dynamically


# ---------------------------------------------------------------------------
# dispatch-table + handler extraction
# ---------------------------------------------------------------------------


def _class_def(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def extract_dispatch(cls: ast.ClassDef, enum_name: str) -> Dict[str, str]:
    """{task type name → handler method name} from ``_process``.

    Understands the dict-dispatch idiom (``{TaskType.X:
    self._handler}.get(task.task_type)``; a lambda value is a declared
    no-op and maps to ``<noop>``) and the guard idiom (``if
    task.task_type == TaskType.X: self._handler(task)``)."""
    proc = None
    for item in cls.body:
        if isinstance(item, ast.FunctionDef) and item.name == "_process":
            proc = item
            break
    if proc is None:
        return {}
    table: Dict[str, str] = {}

    def enum_member(node: ast.expr) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == enum_name
        ):
            return node.attr
        return None

    for node in ast.walk(proc):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                m = enum_member(k) if k is not None else None
                if m is None:
                    continue
                if (
                    isinstance(v, ast.Attribute)
                    and isinstance(v.value, ast.Name)
                    and v.value.id == "self"
                ):
                    table[m] = v.attr
                elif isinstance(v, ast.Lambda):
                    table[m] = "<noop>"
        if isinstance(node, ast.If):
            # if task.task_type == TaskType.X: self._handler(task)
            t = node.test
            if (
                isinstance(t, ast.Compare) and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)
            ):
                m = enum_member(t.comparators[0])
                if m is not None and m not in table:
                    for stmt in node.body:
                        if (
                            isinstance(stmt, ast.Expr)
                            and isinstance(stmt.value, ast.Call)
                            and isinstance(stmt.value.func, ast.Attribute)
                            and isinstance(stmt.value.func.value, ast.Name)
                            and stmt.value.func.value.id == "self"
                        ):
                            table[m] = stmt.value.func.attr
                            break
    return table


def extract_method_footprints(
    cls: ast.ClassDef, module_funcs: Set[str]
) -> Dict[str, ExtractedFootprint]:
    """Per-method footprints with the same-class call closure folded in
    (fixpoint, mirroring oracle_ast.extract_replicate_writes)."""
    methods = {
        item.name for item in cls.body if isinstance(item, ast.FunctionDef)
    }
    out: Dict[str, ExtractedFootprint] = {}
    for item in cls.body:
        if not isinstance(item, ast.FunctionDef):
            continue
        local_names = {a.arg for a in item.args.args}
        local_names |= {a.arg for a in item.args.kwonlyargs}
        for n in ast.walk(item):
            if isinstance(n, ast.FunctionDef) and n is not item:
                local_names.add(n.name)
            if isinstance(n, ast.Assign) and isinstance(
                n.value, ast.Lambda
            ):
                local_names |= {
                    t.id for t in n.targets if isinstance(t, ast.Name)
                }
        v = _EffectVisitor(methods, module_funcs, local_names)
        for stmt in item.body:
            v.visit(stmt)
        out[item.name] = v.fp
    changed = True
    while changed:
        changed = False
        for fp in out.values():
            for callee in list(fp.self_calls):
                target = out.get(callee)
                if target is not None and fp.merge(target):
                    changed = True
    return out


def handler_footprints(repo_root: str) -> Dict[Tuple[str, str], Tuple[
        str, str, Optional[ExtractedFootprint]]]:
    """{(plane, task type) → (module relpath, handler name, footprint)}
    for every dispatch-reachable handler in the tree. A ``<noop>``
    dispatch entry (lambda) yields an empty footprint."""
    out: Dict[Tuple[str, str], Tuple[str, str,
                                     Optional[ExtractedFootprint]]] = {}
    for rel, clsname, plane, enum_name in DISPATCH_CLASSES:
        path = os.path.join(repo_root, rel)
        with open(path) as f:
            tree = ast.parse(f.read())
        module_funcs = {
            n.name for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        cls = _class_def(tree, clsname)
        if cls is None:
            continue
        dispatch = extract_dispatch(cls, enum_name)
        fps = extract_method_footprints(cls, module_funcs)
        for ttype, handler in dispatch.items():
            if handler == "<noop>":
                out[(plane, ttype)] = (rel, handler, ExtractedFootprint())
            else:
                out[(plane, ttype)] = (rel, handler, fps.get(handler))
    for rel, clsname, plane, entry_map in REPLICATION_HANDLERS:
        path = os.path.join(repo_root, rel)
        with open(path) as f:
            tree = ast.parse(f.read())
        module_funcs = {
            n.name for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        cls = _class_def(tree, clsname)
        if cls is None:
            continue
        fps = extract_method_footprints(cls, module_funcs)
        for method, ttype in entry_map.items():
            out[(plane, ttype)] = (rel, method, fps.get(method))
    return out


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


def diff_footprints(
    extracted: Dict[Tuple[str, str],
                    Tuple[str, str, Optional[ExtractedFootprint]]],
    declared: Optional[Dict[Tuple[str, str], object]] = None,
) -> List[Finding]:
    """Diff extracted handler footprints against the declared table."""
    from cadence_tpu.runtime.queues import effects as rt

    if declared is None:
        declared = rt.TASK_FOOTPRINTS
    findings: List[Finding] = []
    for (plane, ttype), (rel, handler, fp) in sorted(extracted.items()):
        anchor = f"queue:{plane}:{ttype}"
        if fp is None:
            findings.append(Finding(
                RULE_UNKNOWN, f"{anchor}:missing-handler",
                f"{rel}: dispatch maps {plane}:{ttype} to {handler} "
                "but no such method exists — unextractable footprint",
            ))
            continue
        for lineno, why in fp.unknown:
            findings.append(Finding(
                RULE_UNKNOWN, f"{anchor}:{why.split('(', 1)[0].strip()}",
                f"{rel}:{lineno}: {plane}:{ttype} handler {handler} has "
                f"an unextractable effect: {why} — add it to the Pass-5 "
                "vocabulary or refactor to a tracked helper",
            ))
        decl = declared.get((plane, ttype))
        if decl is None:
            findings.append(Finding(
                RULE_UNDECLARED, f"{anchor}:undeclared",
                f"{rel}: {plane}:{ttype} ({handler}) has no declared "
                "footprint in runtime/queues/effects.TASK_FOOTPRINTS — "
                "the conflict matrix cannot cover it",
            ))
            continue
        extra_w = sorted(fp.writes - decl.writes)
        if extra_w:
            findings.append(Finding(
                RULE_UNDECLARED, f"{anchor}:writes",
                f"{rel}: {plane}:{ttype} ({handler}) writes "
                f"{','.join(extra_w)} outside its declared footprint — "
                "extend TASK_FOOTPRINTS (and re-derive the conflict "
                "matrix) or remove the effect",
            ))
        # handlers may read anything the plane-common prelude already
        # pays (domain-owner classification), hence PLANE_COMMON_READS
        extra_r = sorted(
            fp.reads - decl.reads - decl.writes - rt.PLANE_COMMON_READS
        )
        if extra_r:
            findings.append(Finding(
                RULE_UNDECLARED, f"{anchor}:reads",
                f"{rel}: {plane}:{ttype} ({handler}) reads "
                f"{','.join(extra_r)} outside its declared footprint",
            ))
        extra_x = sorted(fp.cross_workflow - decl.cross_workflow)
        if extra_x:
            findings.append(Finding(
                RULE_CROSS, f"{anchor}:cross-wf",
                f"{rel}: {plane}:{ttype} ({handler}) fans out across "
                f"workflows ({','.join(extra_x)}) without declaring it "
                "— cross-workflow effects break per-workflow conflict "
                "keying and MUST be explicit in TASK_FOOTPRINTS",
            ))
    return findings


def run(repo_root: str) -> List[Finding]:
    return diff_footprints(handler_footprints(repo_root))


# ---------------------------------------------------------------------------
# conflict-matrix artifact
# ---------------------------------------------------------------------------


def emit_conflict_matrix(repo_root: str, path: str) -> None:
    """Write the task-type commutativity matrix as a versioned JSON
    artifact (the future parallel-queue executor's gate). The matrix
    derives from the DECLARED footprints; the gate (this pass) proves
    declared ⊇ extracted and the chaos witness proves recorded ⊆
    static, so consumers may trust the artifact's verdicts."""
    from cadence_tpu.runtime.queues import effects as rt

    from .artifact import write_artifact

    doc = rt.build_conflict_matrix()
    # ms-column granularity rides along for the executor's future
    # finer-grained keying (informational; verdicts are surface-level)
    cols: Dict[str, Dict[str, List[str]]] = {}
    for (plane, ttype), (_, _, fp) in handler_footprints(repo_root).items():
        if fp is not None:
            cols[f"{plane}:{ttype}"] = {
                "ms_reads": sorted(fp.ms_reads),
                "ms_writes": sorted(fp.ms_writes),
            }
    doc["ms_columns"] = cols
    write_artifact(path, rt.CONFLICT_MATRIX_SCHEMA, doc)
