"""Pass 2 — JIT-hazard lint over the kernel stack and its callers.

Vectorized-kernel throughput lives and dies on trace discipline: a
shape that reaches a ``jit`` boundary unrounded recompiles per batch
size, a host sync (``.item()``, ``float()``, ``np.*`` on a tracer)
serializes the pipeline, a Python-level branch on traced data throws at
trace time or silently constant-folds, and a dtype that widens under
the int16 narrow event stream corrupts values mid-storm. Four AST
rules + one trace-time dtype sweep:

* ``JIT-HOST-SYNC``   — ``.item()`` / ``float()`` / ``bool()`` /
  ``np.*`` calls inside traced functions.
* ``JIT-PY-BRANCH``   — Python ``if``/``while``/conditional-expression
  tests over subscripted array data or ``jnp`` calls inside traced
  functions (``is``/``is not`` None-checks stay legal — that's how
  static specialization is spelled).
* ``JIT-SHAPE-ROUND`` — a function that calls a jit entry point and
  sizes buffers from raw ``len()``/``.shape`` without ever consulting
  ``round_scan_len`` (the geometric shape grid) — the storm-recompile
  hazard.
* ``JIT-NARROW-FORCE-WIDE`` — ``narrow_events_teb`` called without
  ``force_wide``: the wide-column set must only ever grow across a
  storm, or a later batch whose column span happens to fit int16 is
  narrowed under a different specialization AND decoded with the wrong
  base (the int16 widening-corruption hazard).
* ``JIT-DTYPE-WIDEN`` (trace time) — the replay step's jaxpr must stay
  int32/bool end to end; a leaked Python float or int64 promotion
  doubles the HBM stream the scan is bound by.

Traced-function discovery is static: roots are ``jax.jit(...)``
wrappers, ``@jax.jit`` decorations, and kernels handed to
``pallas_call``; the set closes over same-module calls.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from .findings import Finding

NUMPY_ALIASES = {"np", "numpy", "_np"}
JNP_ALIASES = {"jnp"}
SIZED_CTORS = {"empty_state", "zeros", "ones", "full", "empty"}


# --------------------------------------------------------------------------
# Traced-function discovery
# --------------------------------------------------------------------------


def _is_jax_jit(node: ast.expr) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "jit"
        and isinstance(node.value, ast.Name)
        and node.value.id == "jax"
    )


def _callee_name(node: ast.expr) -> Optional[str]:
    """Unwrap ``f`` / ``partial(f, ...)`` / ``functools.partial(f, ...)``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        fn = node.func
        is_partial = (isinstance(fn, ast.Name) and fn.id == "partial") or (
            isinstance(fn, ast.Attribute) and fn.attr == "partial"
        )
        if is_partial and node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
    return None


def _pallas_call_roots(tree: ast.Module) -> Set[str]:
    """Function names handed to ``pallas_call(kernel_or_partial, ...)``
    — the one extraction both the traced-fn rules and the int16
    arithmetic rule scope from, so a new spelling lands in both."""
    roots: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = (
            node.func.attr
            if isinstance(node.func, ast.Attribute)
            else node.func.id if isinstance(node.func, ast.Name) else ""
        )
        if fname == "pallas_call" and node.args:
            name = _callee_name(node.args[0])
            if name:
                roots.add(name)
    return roots


def traced_functions(tree: ast.Module) -> Set[str]:
    """Module-level function names whose bodies run at trace time."""
    fns: Dict[str, ast.FunctionDef] = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }
    roots: Set[str] = set(_pallas_call_roots(tree))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            # X = jax.jit(f, ...)
            if _is_jax_jit(node.func) and node.args:
                name = _callee_name(node.args[0])
                if name:
                    roots.add(name)
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if _is_jax_jit(dec) or (
                    isinstance(dec, ast.Call)
                    and (
                        _is_jax_jit(dec.func)
                        or (
                            dec.args
                            and _is_jax_jit(dec.args[0])
                        )
                    )
                ):
                    roots.add(node.name)

    # call-graph closure within the module
    calls: Dict[str, Set[str]] = {}
    for name, fn in fns.items():
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                out.add(node.func.id)
        calls[name] = out
    traced = {r for r in roots if r in fns}
    frontier = list(traced)
    while frontier:
        cur = frontier.pop()
        for callee in calls.get(cur, ()):
            if callee in fns and callee not in traced:
                traced.add(callee)
                frontier.append(callee)
    return traced


# --------------------------------------------------------------------------
# AST rules
# --------------------------------------------------------------------------


def _contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


def _is_jnp_call(n: ast.AST) -> bool:
    return (
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and isinstance(n.func.value, ast.Name)
        and n.func.value.id in JNP_ALIASES
    )


def _is_static_none_check(test: ast.expr) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    )


def _lint_traced_fn(
    fn: ast.FunctionDef, relpath: str, findings: List[Finding]
) -> None:
    anchor = f"{relpath}:{fn.name}"
    for node in ast.walk(fn):
        # .item() — device→host sync inside a trace
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
        ):
            findings.append(Finding(
                "JIT-HOST-SYNC", f"{anchor}:item",
                f"{relpath}:{node.lineno}: .item() in traced function "
                f"{fn.name} forces a device sync at trace time",
            ))
        # np.* inside a trace: silently materializes the tracer
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in NUMPY_ALIASES
        ):
            findings.append(Finding(
                "JIT-HOST-SYNC", f"{anchor}:np.{node.func.attr}",
                f"{relpath}:{node.lineno}: numpy call "
                f"np.{node.func.attr}(...) in traced function {fn.name} "
                "materializes the tracer on host",
            ))
        # float()/bool() of a dynamic expression
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id in ("float", "bool")
            and node.args
            and not isinstance(node.args[0], ast.Constant)
        ):
            findings.append(Finding(
                "JIT-HOST-SYNC", f"{anchor}:{node.func.id}",
                f"{relpath}:{node.lineno}: {node.func.id}(...) on a "
                f"dynamic value in traced function {fn.name} is a "
                "trace-time host sync (ConcretizationTypeError on "
                "real tracers)",
            ))
        # Python control flow over traced data
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
            if _is_static_none_check(test):
                continue
            if _contains(
                test,
                lambda n: isinstance(n, ast.Subscript) or _is_jnp_call(n),
            ):
                findings.append(Finding(
                    "JIT-PY-BRANCH", f"{anchor}:branch",
                    f"{relpath}:{test.lineno}: Python branch on "
                    f"subscripted/jnp-derived data in traced function "
                    f"{fn.name} — the branch freezes at trace time "
                    "(or raises on a real tracer)",
                ))


def _lint_shape_round(
    fn: ast.FunctionDef,
    relpath: str,
    jit_entries: Set[str],
    findings: List[Finding],
) -> None:
    calls_jit = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = (
                node.func.id
                if isinstance(node.func, ast.Name)
                else node.func.attr
                if isinstance(node.func, ast.Attribute)
                else ""
            )
            if name in jit_entries or name.endswith("_jit"):
                calls_jit = True
    if not calls_jit:
        return
    rounds = _contains(
        fn,
        lambda n: isinstance(n, ast.Call)
        and (
            (isinstance(n.func, ast.Name)
             and n.func.id in ("round_scan_len", "pack_histories",
                              "pack_lanes"))
            or (isinstance(n.func, ast.Attribute)
                and n.func.attr in ("round_scan_len", "pack_histories",
                                    "pack_lanes"))
        ),
    )
    if rounds:
        return
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)):
            continue
        name = (
            node.func.id
            if isinstance(node.func, ast.Name)
            else node.func.attr
            if isinstance(node.func, ast.Attribute)
            else ""
        )
        if name not in SIZED_CTORS or not node.args:
            continue
        size_arg = node.args[0]
        raw_sized = _contains(
            size_arg,
            lambda n: (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id == "len"
            )
            or (isinstance(n, ast.Attribute) and n.attr == "shape"),
        )
        if raw_sized:
            findings.append(Finding(
                "JIT-SHAPE-ROUND", f"{relpath}:{fn.name}:{name}",
                f"{relpath}:{node.lineno}: {fn.name} sizes a buffer from "
                "raw len()/shape and feeds a jit entry point without "
                "round_scan_len — every distinct batch size compiles a "
                "fresh executable",
            ))


def _lint_narrow_force_wide(
    tree: ast.Module, relpath: str, findings: List[Finding]
) -> None:
    seen = 0
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = (
            node.func.id
            if isinstance(node.func, ast.Name)
            else node.func.attr
            if isinstance(node.func, ast.Attribute)
            else ""
        )
        if name != "narrow_events_teb":
            continue
        seen += 1
        if not any(k.arg == "force_wide" for k in node.keywords):
            findings.append(Finding(
                "JIT-NARROW-FORCE-WIDE", f"{relpath}:narrow#{seen}",
                f"{relpath}:{node.lineno}: narrow_events_teb() without "
                "force_wide= — the wide-column set must grow "
                "monotonically across a storm or int16 decoding "
                "corrupts later batches",
            ))


def pallas_kernels(tree: ast.Module) -> Set[str]:
    """Function names handed to ``pallas_call`` (+ same-module call
    closure) — the scope of the int16 arithmetic rule."""
    fns: Dict[str, ast.FunctionDef] = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
    }
    roots = {n for n in _pallas_call_roots(tree) if n in fns}
    frontier = list(roots)
    while frontier:
        cur = frontier.pop()
        for node in ast.walk(fns[cur]):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                if callee in fns and callee not in roots:
                    roots.add(callee)
                    frontier.append(callee)
    return roots


def _is_dtype(node: ast.expr, name: str) -> bool:
    """jnp.int16 / np.int16 / "int16" / int16 spellings."""
    if isinstance(node, ast.Attribute) and node.attr == name:
        return True
    if isinstance(node, ast.Name) and node.id == name:
        return True
    return isinstance(node, ast.Constant) and node.value == name


def _is_astype(node: ast.AST, dtype: str) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "astype"
        and bool(node.args)
        and _is_dtype(node.args[0], dtype)
    )


def _lint_int16_arith(
    fn: ast.FunctionDef, relpath: str, findings: List[Finding]
) -> None:
    """PALLAS-INT16-ARITH — narrow-stream values must widen before
    multiply/accumulate.

    int16 multiplies (and long adds) wrap silently on the VPU: the
    narrow event stream is a transfer/HBM format, never an arithmetic
    one, so every value cast (or loaded) as int16 must pass through
    ``.astype(jnp.int32)`` before feeding ``*``/``+``/``-``. Flags any
    binary arithmetic or augmented assignment whose operand is an int16
    cast, or a local name whose latest cast-assignment above the use is
    one (line-ordered, so re-narrowing after a widen is still caught)."""
    # name -> line-sorted [(lineno, is_narrow)]; a use consults the
    # latest assignment at-or-above its own line, not a whole-function
    # set (x = a.astype(int32) ... x = b.astype(int16); out = x * 3
    # must flag)
    assigns: dict = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        names = {
            t.id for t in node.targets if isinstance(t, ast.Name)
        }
        if not names:
            continue
        if any(_is_astype(n, "int32") for n in ast.walk(node.value)):
            is_narrow = False
        elif any(_is_astype(n, "int16") for n in ast.walk(node.value)):
            is_narrow = True
        else:
            continue
        for nm in names:
            assigns.setdefault(nm, []).append((node.lineno, is_narrow))
    for lst in assigns.values():
        lst.sort()

    def _name_narrow_at(name: str, use_line: int) -> bool:
        state = False
        for ln, is_narrow in assigns.get(name, ()):
            if ln > use_line:
                break
            state = is_narrow
        return state

    def is_narrow_operand(side: ast.expr, use_line: int) -> bool:
        if isinstance(side, ast.Name) and _name_narrow_at(
            side.id, use_line
        ):
            return True
        # a bare cast used inline, or any int16 cast inside the operand
        # expression that is not re-widened above it
        sub = list(ast.walk(side))
        return any(_is_astype(n, "int16") for n in sub) and not any(
            _is_astype(n, "int32") for n in sub
        )

    seen_lines: Set[int] = set()
    for node in ast.walk(fn):
        operands = ()
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Mult, ast.Add, ast.Sub)
        ):
            operands = (node.left, node.right)
        elif isinstance(node, ast.AugAssign) and isinstance(
            node.op, (ast.Mult, ast.Add, ast.Sub)
        ):
            tgt = node.target
            operands = (tgt, node.value) if isinstance(tgt, ast.Name) \
                else (node.value,)
        for side in operands:
            if (
                is_narrow_operand(side, node.lineno)
                and node.lineno not in seen_lines
            ):
                seen_lines.add(node.lineno)
                findings.append(Finding(
                    "PALLAS-INT16-ARITH",
                    f"{relpath}:{fn.name}:int16#{len(seen_lines)}",
                    f"{relpath}:{node.lineno}: int16-narrow value feeds "
                    f"multiply/accumulate in Pallas kernel {fn.name} "
                    "without .astype(jnp.int32) — int16 arithmetic "
                    "wraps silently on the VPU; widen the narrow "
                    "stream before any arithmetic",
                ))
                break


def _jit_entry_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jax_jit(node.value.func):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def lint_source(source: str, relpath: str) -> List[Finding]:
    """All AST rules over one module's source."""
    tree = ast.parse(source)
    findings: List[Finding] = []
    traced = traced_functions(tree)
    kernels = pallas_kernels(tree)
    jit_entries = _jit_entry_names(tree)
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name in traced:
            _lint_traced_fn(node, relpath, findings)
        else:
            _lint_shape_round(node, relpath, jit_entries, findings)
        if node.name in kernels:
            _lint_int16_arith(node, relpath, findings)
    # methods of classes (dispatch pumps) get the shape rule too
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    _lint_shape_round(
                        item, relpath,
                        jit_entries | {"replay_scan_pallas_teb",
                                       "replay_scan_pallas_packed"},
                        findings,
                    )
    _lint_narrow_force_wide(tree, relpath, findings)
    return findings


# --------------------------------------------------------------------------
# Trace-time dtype sweep
# --------------------------------------------------------------------------

ALLOWED_DTYPES = {"int32", "bool"}


def trace_dtype_findings(closed, anchor: str) -> List[Finding]:
    """Flag any intermediate/output aval outside int32/bool in a jaxpr."""
    bad: Dict[str, int] = {}
    jaxpr = closed.jaxpr
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            aval = getattr(ov, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt and dt not in ALLOWED_DTYPES:
                bad[dt] = bad.get(dt, 0) + 1
    return [
        Finding(
            "JIT-DTYPE-WIDEN", f"{anchor}:{dt}",
            f"{anchor}: {n} traced intermediate(s) of dtype {dt} — the "
            "replay carry must stay int32/bool (widening doubles the "
            "HBM stream; floats break bit-parity with the oracle)",
        )
        for dt, n in sorted(bad.items())
    ]


def check_step_dtypes() -> List[Finding]:
    """Dtype sweep of the unspecialized replay step."""
    import jax
    import numpy as np

    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.replay import replay_step_cols, state_to_cols

    caps = S.Capacities(
        max_events=8, max_activities=3, max_timers=2, max_children=2,
        max_request_cancels=2, max_signals_ext=2, max_version_items=2,
    )
    cols = state_to_cols(S.empty_state(4, caps))
    ev = np.zeros((4, S.EV_N), np.int32)
    closed = jax.make_jaxpr(lambda c, e: replay_step_cols(c, e))(cols, ev)
    return trace_dtype_findings(closed, "ops/replay.py:replay_step_cols")


# --------------------------------------------------------------------------
# Orchestration
# --------------------------------------------------------------------------

SCOPE = (
    "cadence_tpu/ops",
    "cadence_tpu/runtime/replication/rebuilder.py",
    "cadence_tpu/checkpoint/manager.py",
)


def run(repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for entry in SCOPE:
        path = os.path.join(repo_root, entry)
        files = []
        if os.path.isdir(path):
            files = [
                os.path.join(path, f)
                for f in sorted(os.listdir(path))
                if f.endswith(".py")
            ]
        elif os.path.isfile(path):
            files = [path]
        for fpath in files:
            rel = os.path.relpath(fpath, repo_root)
            with open(fpath) as f:
                findings += lint_source(f.read(), rel)
    findings += check_step_dtypes()
    return findings
