// Packing/transport sidecar.
//
// The north-star architecture (SURVEY.md §2.8) calls for a native
// sidecar between the host control plane and the device path: it owns
// the numeric, bandwidth-bound steps of packing — scattering ragged
// per-workflow event rows into the dense time-major [T, B, E] tensor
// the replay scan consumes — plus the transport codec that ships those
// tensors across hosts (varint+zigzag delta compression; event tensors
// are small-valued and monotone, so this typically shrinks them 4-8x
// before they hit DCN).
//
// Exposed via a C ABI for ctypes (pybind11 is not available in this
// image). All buffers are caller-allocated numpy arrays.

#include <cstdint>
#include <cstring>

extern "C" {

// -- scatter: ragged rows -> dense time-major ---------------------------
//
// rows:    [total_events, ev_n] int32, workflows concatenated in order
// lengths: [batch] int64, events per workflow (sum == total_events)
// out:     [max_events, batch, ev_n] int32, fully overwritten:
//          valid slots get their row; padding gets type_pad at column 0
//          (EV_TYPE) and zeros elsewhere.
void ct_scatter_time_major(const int32_t* rows, const int64_t* lengths,
                           int64_t batch, int64_t ev_n, int64_t max_events,
                           int32_t type_pad, int32_t* out) {
    // one sequential pass over the destination (the big buffer):
    // per (t, b) either copy the event row or write the padding row
    const int64_t plane = batch * ev_n;
    // per-workflow source cursors
    const int32_t** srcs = new const int32_t*[batch];
    {
        const int32_t* p = rows;
        for (int64_t b = 0; b < batch; ++b) {
            srcs[b] = p;
            p += lengths[b] * ev_n;
        }
    }
    // rows are short (EV_N ~ a dozen int32) — an open-coded copy beats
    // a memcpy call per row
    for (int64_t t = 0; t < max_events; ++t) {
        int32_t* dst = out + t * plane;
        for (int64_t b = 0; b < batch; ++b, dst += ev_n) {
            if (t < lengths[b]) {
                const int32_t* s = srcs[b] + t * ev_n;
                for (int64_t k = 0; k < ev_n; ++k) dst[k] = s[k];
            } else {
                dst[0] = type_pad;  // EV_TYPE padding sentinel
                for (int64_t k = 1; k < ev_n; ++k) dst[k] = 0;
            }
        }
    }
    delete[] srcs;
}

// batch-major variant: out [batch, max_events, ev_n]
void ct_scatter_batch_major(const int32_t* rows, const int64_t* lengths,
                            int64_t batch, int64_t ev_n, int64_t max_events,
                            int32_t type_pad, int32_t* out) {
    const int64_t plane = max_events * ev_n;
    std::memset(out, 0, sizeof(int32_t) * batch * plane);
    for (int64_t b = 0; b < batch; ++b) {
        int32_t* wf = out + b * plane;
        for (int64_t t = 0; t < max_events; ++t) {
            wf[t * ev_n] = type_pad;
        }
    }
    const int32_t* src = rows;
    for (int64_t b = 0; b < batch; ++b) {
        const int64_t n = lengths[b];
        // clamp to the plane: an oversized workflow copies its first
        // max_events rows (mirrors the time-major loop bound) instead
        // of overrunning the destination
        const int64_t n_copy = n < max_events ? n : max_events;
        if (n_copy > 0) {
            std::memcpy(out + b * plane, src, sizeof(int32_t) * n_copy * ev_n);
        }
        src += n * ev_n;
    }
}

// -- hashing ------------------------------------------------------------
//
// FNV-1a 32-bit over each string, masked to 31 bits (the packer's
// hash31 for id -> integer-slot-key pre-hashing).
void ct_fnv1a32_batch(const char* data, const int64_t* offsets,
                      int64_t n, uint32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint32_t h = 2166136261u;
        for (int64_t p = offsets[i]; p < offsets[i + 1]; ++p) {
            h ^= (uint8_t)data[p];
            h *= 16777619u;
        }
        out[i] = h & 0x7fffffffu;
    }
}

// -- transport codec ----------------------------------------------------
//
// zigzag + varint over int32 deltas (consecutive values in packed event
// tensors are strongly correlated). Encoded layout: varint(count) then
// count varints of zigzag(delta).

static inline uint32_t zigzag32(int32_t v) {
    return ((uint32_t)v << 1) ^ (uint32_t)(v >> 31);
}

static inline int32_t unzigzag32(uint32_t v) {
    return (int32_t)(v >> 1) ^ -(int32_t)(v & 1);
}

static inline uint8_t* put_varint(uint8_t* p, uint32_t v) {
    while (v >= 0x80) {
        *p++ = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    *p++ = (uint8_t)v;
    return p;
}

// bounded read: returns the advanced cursor, or nullptr on truncation
// or an overlong (>5 byte) varint — corrupt transport input is a
// realistic failure mode for the DCN codec.
static inline const uint8_t* get_varint(const uint8_t* p, const uint8_t* end,
                                        uint32_t* v) {
    uint32_t out = 0;
    int shift = 0;
    while (true) {
        if (p >= end || shift > 28) return nullptr;
        uint8_t b = *p++;
        out |= (uint32_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    *v = out;
    return p;
}

// Worst case: 5 bytes per value + 5-byte header.
int64_t ct_compress_bound(int64_t n) { return 5 * n + 5; }

// returns encoded byte count
int64_t ct_tensor_compress(const int32_t* data, int64_t n, uint8_t* out) {
    uint8_t* p = put_varint(out, (uint32_t)n);
    int32_t prev = 0;
    for (int64_t i = 0; i < n; ++i) {
        p = put_varint(p, zigzag32(data[i] - prev));
        prev = data[i];
    }
    return (int64_t)(p - out);
}

// returns decoded element count (caller sized `out` via the header),
// or -1 on a truncated / malformed blob
int64_t ct_tensor_decompress(const uint8_t* blob, int64_t blob_len,
                             int32_t* out) {
    const uint8_t* end = blob + blob_len;
    uint32_t n;
    const uint8_t* p = get_varint(blob, end, &n);
    if (p == nullptr) return -1;
    int32_t prev = 0;
    for (uint32_t i = 0; i < n; ++i) {
        uint32_t z;
        p = get_varint(p, end, &z);
        if (p == nullptr) return -1;
        prev = (int32_t)((uint32_t)prev + (uint32_t)unzigzag32(z));
        out[i] = prev;
    }
    return (int64_t)n;
}

// peek the element count without decoding; -1 on malformed header
int64_t ct_tensor_peek_count(const uint8_t* blob, int64_t blob_len) {
    uint32_t n;
    if (get_varint(blob, blob + blob_len, &n) == nullptr) return -1;
    return (int64_t)n;
}

}  // extern "C"
