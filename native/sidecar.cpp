// Packing/transport sidecar.
//
// The north-star architecture (SURVEY.md §2.8) calls for a native
// sidecar between the host control plane and the device path: it owns
// the numeric, bandwidth-bound steps of packing — scattering ragged
// per-workflow event rows into the dense time-major [T, B, E] tensor
// the replay scan consumes — plus the transport codec that ships those
// tensors across hosts (varint+zigzag delta compression; event tensors
// are small-valued and monotone, so this typically shrinks them 4-8x
// before they hit DCN).
//
// Exposed via a C ABI for ctypes (pybind11 is not available in this
// image). All buffers are caller-allocated numpy arrays.

#include <cstdint>
#include <cstring>

extern "C" {

// -- scatter: ragged rows -> dense time-major ---------------------------
//
// rows:    [total_events, ev_n] int32, workflows concatenated in order
// lengths: [batch] int64, events per workflow (sum == total_events)
// out:     [max_events, batch, ev_n] int32, fully overwritten:
//          valid slots get their row; padding gets type_pad at column 0
//          (EV_TYPE) and zeros elsewhere.
void ct_scatter_time_major(const int32_t* rows, const int64_t* lengths,
                           int64_t batch, int64_t ev_n, int64_t max_events,
                           int32_t type_pad, int32_t* out) {
    // one sequential pass over the destination (the big buffer):
    // per (t, b) either copy the event row or write the padding row
    const int64_t plane = batch * ev_n;
    // per-workflow source cursors
    const int32_t** srcs = new const int32_t*[batch];
    {
        const int32_t* p = rows;
        for (int64_t b = 0; b < batch; ++b) {
            srcs[b] = p;
            p += lengths[b] * ev_n;
        }
    }
    // rows are short (EV_N ~ a dozen int32) — an open-coded copy beats
    // a memcpy call per row
    for (int64_t t = 0; t < max_events; ++t) {
        int32_t* dst = out + t * plane;
        for (int64_t b = 0; b < batch; ++b, dst += ev_n) {
            if (t < lengths[b]) {
                const int32_t* s = srcs[b] + t * ev_n;
                for (int64_t k = 0; k < ev_n; ++k) dst[k] = s[k];
            } else {
                dst[0] = type_pad;  // EV_TYPE padding sentinel
                for (int64_t k = 1; k < ev_n; ++k) dst[k] = 0;
            }
        }
    }
    delete[] srcs;
}

// batch-major variant: out [batch, max_events, ev_n]
void ct_scatter_batch_major(const int32_t* rows, const int64_t* lengths,
                            int64_t batch, int64_t ev_n, int64_t max_events,
                            int32_t type_pad, int32_t* out) {
    const int64_t plane = max_events * ev_n;
    std::memset(out, 0, sizeof(int32_t) * batch * plane);
    for (int64_t b = 0; b < batch; ++b) {
        int32_t* wf = out + b * plane;
        for (int64_t t = 0; t < max_events; ++t) {
            wf[t * ev_n] = type_pad;
        }
    }
    const int32_t* src = rows;
    for (int64_t b = 0; b < batch; ++b) {
        const int64_t n = lengths[b];
        // clamp to the plane: an oversized workflow copies its first
        // max_events rows (mirrors the time-major loop bound) instead
        // of overrunning the destination
        const int64_t n_copy = n < max_events ? n : max_events;
        if (n_copy > 0) {
            std::memcpy(out + b * plane, src, sizeof(int32_t) * n_copy * ev_n);
        }
        src += n * ev_n;
    }
}

// field-major variant: out [max_events, ev_n, batch] — the layout the
// Pallas replay kernel consumes directly (per-field planes with batch as
// the contiguous minor dim, so each grid step's event block and the
// presence pass read contiguous rows). Producing it here makes the
// device-side transpose — which costs more than the whole replay scan at
// large batch — disappear from the replay path.
void ct_scatter_teb(const int32_t* rows, const int64_t* lengths,
                    int64_t batch, int64_t ev_n, int64_t max_events,
                    int32_t type_pad, int32_t* out) {
    const int64_t plane = ev_n * batch;
    const int32_t** srcs = new const int32_t*[batch];
    {
        const int32_t* p = rows;
        for (int64_t b = 0; b < batch; ++b) {
            srcs[b] = p;
            p += lengths[b] * ev_n;
        }
    }
    // writes are contiguous per (t, field) run; reads of the source rows
    // are blocked over lanes so each block's rows stay cache-resident
    // across the ev_n field passes
    const int64_t BLK = 512;
    for (int64_t t = 0; t < max_events; ++t) {
        int32_t* tp = out + t * plane;
        for (int64_t b0 = 0; b0 < batch; b0 += BLK) {
            const int64_t b1 = b0 + BLK < batch ? b0 + BLK : batch;
            for (int64_t f = 0; f < ev_n; ++f) {
                int32_t* dst = tp + f * batch;
                const int32_t pad = f == 0 ? type_pad : 0;
                for (int64_t b = b0; b < b1; ++b) {
                    dst[b] = t < lengths[b] ? srcs[b][t * ev_n + f] : pad;
                }
            }
        }
    }
    delete[] srcs;
}

// per-(batch-tile, step) presence bitmasks for the Pallas replay kernel:
// out [n_bt, max_events, 4] int32 with n_bt = batch / bt (batch must be a
// multiple of bt). Words 0-1: event-type bitmask (bit e of word e/32 set
// iff some lane of the tile has type e at step t); word 2: slot bitmask
// (bit s%32); word 3: zero padding. Computing this during packing costs
// one pass over the ragged rows, replacing a device-side reduction over
// the full event tensor on every replay.
void ct_presence(const int32_t* rows, const int64_t* lengths,
                 int64_t batch, int64_t ev_n, int64_t max_events,
                 int64_t bt, int32_t* out) {
    const int64_t n_bt = batch / bt;
    std::memset(out, 0, sizeof(int32_t) * n_bt * max_events * 4);
    const int32_t* src = rows;
    for (int64_t b = 0; b < batch; ++b) {
        int32_t* tile = out + (b / bt) * max_events * 4;
        const int64_t n = lengths[b] < max_events ? lengths[b] : max_events;
        for (int64_t t = 0; t < n; ++t, src += ev_n) {
            const int32_t et = src[0];   // EV_TYPE
            const int32_t sl = src[7];   // EV_SLOT
            if (et < 0) continue;
            int32_t* w = tile + t * 4;
            w[et >= 32 ? 1 : 0] |= (int32_t)1 << (et & 31);
            if (sl >= 0) w[2] |= (int32_t)1 << (sl & 31);
        }
        src += (lengths[b] - n) * ev_n;
    }
}

// -- hashing ------------------------------------------------------------
//
// FNV-1a 32-bit over each string, masked to 31 bits (the packer's
// hash31 for id -> integer-slot-key pre-hashing).
void ct_fnv1a32_batch(const char* data, const int64_t* offsets,
                      int64_t n, uint32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint32_t h = 2166136261u;
        for (int64_t p = offsets[i]; p < offsets[i + 1]; ++p) {
            h ^= (uint8_t)data[p];
            h *= 16777619u;
        }
        out[i] = h & 0x7fffffffu;
    }
}

// -- transport codec ----------------------------------------------------
//
// zigzag + varint over int32 deltas (consecutive values in packed event
// tensors are strongly correlated). Encoded layout: varint(count) then
// count varints of zigzag(delta).

static inline uint32_t zigzag32(int32_t v) {
    return ((uint32_t)v << 1) ^ (uint32_t)(v >> 31);
}

static inline int32_t unzigzag32(uint32_t v) {
    return (int32_t)(v >> 1) ^ -(int32_t)(v & 1);
}

static inline uint8_t* put_varint(uint8_t* p, uint32_t v) {
    while (v >= 0x80) {
        *p++ = (uint8_t)(v | 0x80);
        v >>= 7;
    }
    *p++ = (uint8_t)v;
    return p;
}

// bounded read: returns the advanced cursor, or nullptr on truncation
// or an overlong (>5 byte) varint — corrupt transport input is a
// realistic failure mode for the DCN codec.
static inline const uint8_t* get_varint(const uint8_t* p, const uint8_t* end,
                                        uint32_t* v) {
    uint32_t out = 0;
    int shift = 0;
    while (true) {
        if (p >= end || shift > 28) return nullptr;
        uint8_t b = *p++;
        out |= (uint32_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    *v = out;
    return p;
}

// Worst case: 5 bytes per value + 5-byte header.
int64_t ct_compress_bound(int64_t n) { return 5 * n + 5; }

// returns encoded byte count
int64_t ct_tensor_compress(const int32_t* data, int64_t n, uint8_t* out) {
    uint8_t* p = put_varint(out, (uint32_t)n);
    int32_t prev = 0;
    for (int64_t i = 0; i < n; ++i) {
        p = put_varint(p, zigzag32(data[i] - prev));
        prev = data[i];
    }
    return (int64_t)(p - out);
}

// returns decoded element count (caller sized `out` via the header),
// or -1 on a truncated / malformed blob
int64_t ct_tensor_decompress(const uint8_t* blob, int64_t blob_len,
                             int32_t* out) {
    const uint8_t* end = blob + blob_len;
    uint32_t n;
    const uint8_t* p = get_varint(blob, end, &n);
    if (p == nullptr) return -1;
    int32_t prev = 0;
    for (uint32_t i = 0; i < n; ++i) {
        uint32_t z;
        p = get_varint(p, end, &z);
        if (p == nullptr) return -1;
        prev = (int32_t)((uint32_t)prev + (uint32_t)unzigzag32(z));
        out[i] = prev;
    }
    return (int64_t)n;
}

// peek the element count without decoding; -1 on malformed header
int64_t ct_tensor_peek_count(const uint8_t* blob, int64_t blob_len) {
    uint32_t n;
    if (get_varint(blob, blob + blob_len, &n) == nullptr) return -1;
    return (int64_t)n;
}

// -- sequential replayer ------------------------------------------------
//
// The compiled-host baseline: replays packed histories one workflow at
// a time, one event at a time, with the exact transition semantics of
// the TPU kernel (cadence_tpu/ops/replay.py == the host oracle
// cadence_tpu/core/state_builder.py == the reference's
// stateBuilder.applyEvents loop, service/history/stateBuilder.go:112-613).
// This is what an optimized single-thread CPU implementation of the
// replay loop looks like — bench.py measures the TPU kernel's speedup
// against it, not against interpreted Python.
//
// Column layout constants mirror cadence_tpu/ops/schema.py; the
// differential test (tests/test_native_replayer.py) asserts bit-for-bit
// parity with the kernel, which pins both the constants and the
// semantics.

namespace {

// EventType (cadence_tpu/core/enums.py)
enum {
    EV_WF_STARTED = 0, EV_WF_COMPLETED = 1, EV_WF_FAILED = 2,
    EV_WF_TIMEDOUT = 3, EV_DEC_SCHEDULED = 4, EV_DEC_STARTED = 5,
    EV_DEC_COMPLETED = 6, EV_DEC_TIMEDOUT = 7, EV_DEC_FAILED = 8,
    EV_ACT_SCHEDULED = 9, EV_ACT_STARTED = 10, EV_ACT_COMPLETED = 11,
    EV_ACT_FAILED = 12, EV_ACT_TIMEDOUT = 13, EV_ACT_CANCEL_REQ = 14,
    EV_ACT_CANCELED = 16, EV_TIMER_STARTED = 17, EV_TIMER_FIRED = 18,
    EV_TIMER_CANCELED = 20, EV_WF_CANCEL_REQ = 21, EV_WF_CANCELED = 22,
    EV_RC_INITIATED = 23, EV_RC_FAILED = 24, EV_RC_EXT_REQUESTED = 25,
    EV_WF_SIGNALED = 27, EV_WF_TERMINATED = 28, EV_WF_CONTINUED = 29,
    EV_CHILD_INITIATED = 30, EV_CHILD_INIT_FAILED = 31,
    EV_CHILD_STARTED = 32, EV_CHILD_COMPLETED = 33, EV_CHILD_FAILED = 34,
    EV_CHILD_CANCELED = 35, EV_CHILD_TIMEDOUT = 36,
    EV_CHILD_TERMINATED = 37, EV_SG_INITIATED = 38, EV_SG_FAILED = 39,
    EV_SG_EXT_SIGNALED = 40,
};

// event row columns (schema.py EV_*)
enum { C_TYPE = 0, C_ID = 1, C_VERSION = 2, C_TASK_ID = 3, C_TS = 4,
       C_BATCH_FIRST = 5, C_IS_BATCH_LAST = 6, C_SLOT = 7, C_A0 = 8 };
constexpr int EV_N = 16;

// exec-info columns (schema.py X_*)
enum { X_STATE = 0, X_CLOSE_STATUS = 1, X_NEXT_EVENT_ID = 2,
       X_LAST_FIRST_EVENT_ID = 3, X_LAST_EVENT_TASK_ID = 4,
       X_LAST_PROCESSED_EVENT = 5, X_START_TS = 6, X_WORKFLOW_TIMEOUT = 7,
       X_DECISION_TIMEOUT_VALUE = 8, X_DEC_VERSION = 9,
       X_DEC_SCHEDULE_ID = 10, X_DEC_STARTED_ID = 11, X_DEC_TIMEOUT = 12,
       X_DEC_ATTEMPT = 13, X_DEC_SCHEDULED_TS = 14, X_DEC_STARTED_TS = 15,
       X_DEC_ORIGINAL_SCHEDULED_TS = 16, X_CANCEL_REQUESTED = 17,
       X_SIGNAL_COUNT = 18, X_ATTEMPT = 19, X_HAS_RETRY_POLICY = 20,
       X_COMPLETION_EVENT_BATCH_ID = 21, X_PARENT_INITIATED_ID = 22,
       X_WF_EXPIRATION_TS = 23, X_CUR_VERSION = 24 };
constexpr int X_N = 25;

// activity slot columns (schema.py AC_*)
enum { AC_OCC = 0, AC_VERSION = 1, AC_SCHEDULE_ID = 2,
       AC_SCHEDULED_BATCH_ID = 3, AC_SCHEDULED_TS = 4, AC_STARTED_ID = 5,
       AC_STARTED_TS = 6, AC_ID_HASH = 7, AC_SCH_TO_START = 8,
       AC_SCH_TO_CLOSE = 9, AC_START_TO_CLOSE = 10, AC_HEARTBEAT = 11,
       AC_CANCEL_REQUESTED = 12, AC_CANCEL_REQUEST_ID = 13,
       AC_ATTEMPT = 14, AC_HAS_RETRY = 15, AC_EXPIRATION_TS = 16,
       AC_LAST_HB_TS = 17, AC_TIMER_STATUS = 18 };
constexpr int AC_N = 19;

enum { TI_OCC = 0, TI_VERSION = 1, TI_STARTED_ID = 2, TI_ID_HASH = 3,
       TI_EXPIRY_TS = 4, TI_STATUS = 5 };
constexpr int TI_N = 6;

enum { CH_OCC = 0, CH_VERSION = 1, CH_INITIATED_ID = 2,
       CH_INITIATED_BATCH_ID = 3, CH_STARTED_ID = 4, CH_WF_ID_HASH = 5,
       CH_RUN_ID_HASH = 6, CH_POLICY = 7 };
constexpr int CH_N = 8;

constexpr int RC_N = 4;  // OCC, VERSION, INITIATED_ID, INITIATED_BATCH_ID
constexpr int SG_N = 4;

constexpr int32_t EMPTY_EVENT_ID = -23;
constexpr int32_t EMPTY_VERSION = -24;
constexpr int32_t WF_STATE_CREATED = 0, WF_STATE_RUNNING = 1,
                  WF_STATE_COMPLETED = 2;
constexpr int32_t TIMEOUT_SCHEDULE_TO_START = 1;

inline void clear_row(int32_t* row, int n) {
    for (int k = 0; k < n; ++k) row[k] = 0;
}

}  // namespace

void ct_replay_sequential(
    const int32_t* events, const int64_t* lengths, int64_t batch, int64_t T,
    int64_t cap_a, int64_t cap_t, int64_t cap_c, int64_t cap_rc,
    int64_t cap_sg, int64_t cap_v,
    int32_t* exec_info, int32_t* activities, int32_t* timers,
    int32_t* children, int32_t* cancels, int32_t* signals,
    int32_t* vh_items, int32_t* vh_len) {
    for (int64_t b = 0; b < batch; ++b) {
        int32_t* ex = exec_info + b * X_N;
        int32_t* act = activities + b * cap_a * AC_N;
        int32_t* tim = timers + b * cap_t * TI_N;
        int32_t* chd = children + b * cap_c * CH_N;
        int32_t* rc = cancels + b * cap_rc * RC_N;
        int32_t* sg = signals + b * cap_sg * SG_N;
        int32_t* vh = vh_items + b * cap_v * 2;
        const int64_t n = lengths[b] < T ? lengths[b] : T;
        for (int64_t t = 0; t < n; ++t) {
            const int32_t* ev = events + (b * T + t) * EV_N;
            const int32_t et = ev[C_TYPE];
            if (et < 0) continue;
            const int32_t ev_id = ev[C_ID], version = ev[C_VERSION];
            const int32_t ts = ev[C_TS], batch_first = ev[C_BATCH_FIRST];
            const int32_t slot = ev[C_SLOT];
            const int32_t a0 = ev[C_A0], a1 = ev[C_A0 + 1],
                          a2 = ev[C_A0 + 2], a3 = ev[C_A0 + 3],
                          a4 = ev[C_A0 + 4], a5 = ev[C_A0 + 5],
                          a6 = ev[C_A0 + 6], a7 = ev[C_A0 + 7];

            // preamble (stateBuilder.go:134-155)
            ex[X_LAST_EVENT_TASK_ID] = ev[C_TASK_ID];
            ex[X_CUR_VERSION] = version;
            ex[X_NEXT_EVENT_ID] = ev_id + 1;
            ex[X_LAST_FIRST_EVENT_ID] = batch_first;

            // version-history AddOrUpdateItem. Mirrors the XLA kernel
            // exactly when vh_len outgrows cap_v: the READ clamps to
            // cap-1 (jnp.take_along_axis) and a same-branch write at
            // an index >= cap is dropped (the kernel's arange mask) —
            // the unclamped original indexed past this workflow's
            // window (cross-row corruption / heap write at b = B-1)
            {
                const int32_t len = vh_len[b];
                const int32_t cap = (int32_t)cap_v;
                const int32_t last_idx = len > 0 ? len - 1 : 0;
                const int32_t read_idx =
                    last_idx < cap ? last_idx : cap - 1;
                const bool same =
                    len > 0 && vh[read_idx * 2 + 1] == version;
                const int32_t wi =
                    same ? last_idx : (len < cap - 1 ? len : cap - 1);
                if (wi < cap) {
                    vh[wi * 2] = ev_id;
                    vh[wi * 2 + 1] = version;
                }
                if (!same) vh_len[b] = len + 1;
            }

            switch (et) {
            case EV_WF_STARTED:
                ex[X_STATE] = WF_STATE_CREATED;
                ex[X_CLOSE_STATUS] = 0;
                ex[X_LAST_PROCESSED_EVENT] = EMPTY_EVENT_ID;
                ex[X_START_TS] = ts;
                ex[X_WORKFLOW_TIMEOUT] = a0;
                ex[X_DECISION_TIMEOUT_VALUE] = a1;
                ex[X_ATTEMPT] = a2;
                ex[X_HAS_RETRY_POLICY] = a3;
                ex[X_WF_EXPIRATION_TS] = a4;
                ex[X_PARENT_INITIATED_ID] = a7;
                ex[X_DEC_SCHEDULE_ID] = EMPTY_EVENT_ID;
                ex[X_DEC_STARTED_ID] = EMPTY_EVENT_ID;
                ex[X_DEC_VERSION] = EMPTY_VERSION;
                ex[X_DEC_TIMEOUT] = 0;
                ex[X_DEC_ATTEMPT] = 0;
                ex[X_DEC_SCHEDULED_TS] = 0;
                ex[X_DEC_STARTED_TS] = 0;
                ex[X_DEC_ORIGINAL_SCHEDULED_TS] = 0;
                break;
            case EV_WF_COMPLETED: case EV_WF_FAILED: case EV_WF_TIMEDOUT:
            case EV_WF_CANCELED: case EV_WF_TERMINATED: case EV_WF_CONTINUED: {
                // CloseStatus: Completed=1 Failed=2 Canceled=3 Terminated=4
                // ContinuedAsNew=5 TimedOut=6
                int32_t cs = 0;
                switch (et) {
                case EV_WF_COMPLETED: cs = 1; break;
                case EV_WF_FAILED: cs = 2; break;
                case EV_WF_TIMEDOUT: cs = 6; break;
                case EV_WF_CANCELED: cs = 3; break;
                case EV_WF_TERMINATED: cs = 4; break;
                case EV_WF_CONTINUED: cs = 5; break;
                }
                ex[X_STATE] = WF_STATE_COMPLETED;
                ex[X_CLOSE_STATUS] = cs;
                ex[X_COMPLETION_EVENT_BATCH_ID] = batch_first;
                break;
            }
            case EV_WF_CANCEL_REQ:
                ex[X_CANCEL_REQUESTED] = 1;
                break;
            case EV_WF_SIGNALED:
                ex[X_SIGNAL_COUNT] += 1;
                break;
            case EV_DEC_SCHEDULED:
                ex[X_DEC_VERSION] = version;
                ex[X_DEC_SCHEDULE_ID] = ev_id;
                ex[X_DEC_STARTED_ID] = EMPTY_EVENT_ID;
                ex[X_DEC_TIMEOUT] = a0;
                ex[X_DEC_ATTEMPT] = a1;
                ex[X_DEC_SCHEDULED_TS] = ts;
                ex[X_DEC_ORIGINAL_SCHEDULED_TS] = ts;
                ex[X_DEC_STARTED_TS] = 0;
                break;
            case EV_DEC_STARTED:
                if (ex[X_STATE] == WF_STATE_CREATED)
                    ex[X_STATE] = WF_STATE_RUNNING;
                ex[X_DEC_VERSION] = version;
                ex[X_DEC_STARTED_ID] = ev_id;
                ex[X_DEC_ATTEMPT] = 0;  // replication magic (:216-224)
                ex[X_DEC_STARTED_TS] = ts;
                break;
            case EV_DEC_COMPLETED:
                ex[X_DEC_VERSION] = EMPTY_VERSION;
                ex[X_DEC_SCHEDULE_ID] = EMPTY_EVENT_ID;
                ex[X_DEC_STARTED_ID] = EMPTY_EVENT_ID;
                ex[X_DEC_TIMEOUT] = 0;
                ex[X_DEC_ATTEMPT] = 0;
                ex[X_DEC_SCHEDULED_TS] = 0;
                ex[X_DEC_STARTED_TS] = 0;
                ex[X_LAST_PROCESSED_EVENT] = a0;
                break;
            case EV_DEC_TIMEDOUT: case EV_DEC_FAILED: {
                const bool increment =
                    et == EV_DEC_FAILED || a0 != TIMEOUT_SCHEDULE_TO_START;
                if (increment) {
                    const int32_t new_attempt = ex[X_DEC_ATTEMPT] + 1;
                    ex[X_DEC_VERSION] = ex[X_CUR_VERSION];
                    ex[X_DEC_SCHEDULE_ID] = batch_first;
                    ex[X_DEC_STARTED_ID] = EMPTY_EVENT_ID;
                    ex[X_DEC_TIMEOUT] = ex[X_DECISION_TIMEOUT_VALUE];
                    ex[X_DEC_ATTEMPT] = new_attempt;
                    ex[X_DEC_SCHEDULED_TS] = ts;
                    ex[X_DEC_STARTED_TS] = 0;
                    ex[X_DEC_ORIGINAL_SCHEDULED_TS] = 0;
                } else {
                    ex[X_DEC_VERSION] = EMPTY_VERSION;
                    ex[X_DEC_SCHEDULE_ID] = EMPTY_EVENT_ID;
                    ex[X_DEC_STARTED_ID] = EMPTY_EVENT_ID;
                    ex[X_DEC_TIMEOUT] = 0;
                    ex[X_DEC_ATTEMPT] = 0;
                    ex[X_DEC_SCHEDULED_TS] = 0;
                    ex[X_DEC_STARTED_TS] = 0;
                    ex[X_DEC_ORIGINAL_SCHEDULED_TS] = 0;
                }
                break;
            }
            case EV_ACT_SCHEDULED: {
                if (slot < 0 || slot >= cap_a) break;
                int32_t* row = act + slot * AC_N;
                const int32_t exp_interval =
                    (a5 > 0 && a6 > a2) ? a6 : a2;
                row[AC_OCC] = 1;
                row[AC_VERSION] = version;
                row[AC_SCHEDULE_ID] = ev_id;
                row[AC_SCHEDULED_BATCH_ID] = batch_first;
                row[AC_SCHEDULED_TS] = ts;
                row[AC_STARTED_ID] = EMPTY_EVENT_ID;
                row[AC_STARTED_TS] = 0;
                row[AC_ID_HASH] = a0;
                row[AC_SCH_TO_START] = a1;
                row[AC_SCH_TO_CLOSE] = a2;
                row[AC_START_TO_CLOSE] = a3;
                row[AC_HEARTBEAT] = a4;
                row[AC_CANCEL_REQUESTED] = 0;
                row[AC_CANCEL_REQUEST_ID] = EMPTY_EVENT_ID;
                row[AC_ATTEMPT] = 0;
                row[AC_HAS_RETRY] = a5;
                row[AC_EXPIRATION_TS] = ts + exp_interval;
                row[AC_LAST_HB_TS] = 0;
                row[AC_TIMER_STATUS] = 0;
                break;
            }
            case EV_ACT_STARTED: {
                if (slot < 0 || slot >= cap_a) break;
                int32_t* row = act + slot * AC_N;
                row[AC_VERSION] = version;
                row[AC_STARTED_ID] = ev_id;
                row[AC_STARTED_TS] = ts;
                row[AC_LAST_HB_TS] = ts;
                row[AC_ATTEMPT] = a1;
                break;
            }
            case EV_ACT_COMPLETED: case EV_ACT_FAILED:
            case EV_ACT_TIMEDOUT: case EV_ACT_CANCELED:
                if (slot >= 0 && slot < cap_a)
                    clear_row(act + slot * AC_N, AC_N);
                break;
            case EV_ACT_CANCEL_REQ: {
                if (slot < 0 || slot >= cap_a) break;
                int32_t* row = act + slot * AC_N;
                row[AC_VERSION] = version;
                row[AC_CANCEL_REQUESTED] = 1;
                row[AC_CANCEL_REQUEST_ID] = ev_id;
                break;
            }
            case EV_TIMER_STARTED: {
                if (slot < 0 || slot >= cap_t) break;
                int32_t* row = tim + slot * TI_N;
                row[TI_OCC] = 1;
                row[TI_VERSION] = version;
                row[TI_STARTED_ID] = ev_id;
                row[TI_ID_HASH] = a0;
                row[TI_EXPIRY_TS] = ts + a1;
                row[TI_STATUS] = 0;
                break;
            }
            case EV_TIMER_FIRED: case EV_TIMER_CANCELED:
                if (slot >= 0 && slot < cap_t)
                    clear_row(tim + slot * TI_N, TI_N);
                break;
            case EV_CHILD_INITIATED: {
                if (slot < 0 || slot >= cap_c) break;
                int32_t* row = chd + slot * CH_N;
                row[CH_OCC] = 1;
                row[CH_VERSION] = version;
                row[CH_INITIATED_ID] = ev_id;
                row[CH_INITIATED_BATCH_ID] = batch_first;
                row[CH_STARTED_ID] = EMPTY_EVENT_ID;
                row[CH_WF_ID_HASH] = a0;
                row[CH_RUN_ID_HASH] = 0;
                row[CH_POLICY] = a1;
                break;
            }
            case EV_CHILD_STARTED: {
                if (slot < 0 || slot >= cap_c) break;
                int32_t* row = chd + slot * CH_N;
                row[CH_STARTED_ID] = ev_id;
                row[CH_RUN_ID_HASH] = a1;
                break;
            }
            case EV_CHILD_INIT_FAILED: case EV_CHILD_COMPLETED:
            case EV_CHILD_FAILED: case EV_CHILD_CANCELED:
            case EV_CHILD_TIMEDOUT: case EV_CHILD_TERMINATED:
                if (slot >= 0 && slot < cap_c)
                    clear_row(chd + slot * CH_N, CH_N);
                break;
            case EV_RC_INITIATED: {
                if (slot < 0 || slot >= cap_rc) break;
                int32_t* row = rc + slot * RC_N;
                row[0] = 1; row[1] = version; row[2] = ev_id;
                row[3] = batch_first;
                break;
            }
            case EV_RC_FAILED: case EV_RC_EXT_REQUESTED:
                if (slot >= 0 && slot < cap_rc)
                    clear_row(rc + slot * RC_N, RC_N);
                break;
            case EV_SG_INITIATED: {
                if (slot < 0 || slot >= cap_sg) break;
                int32_t* row = sg + slot * SG_N;
                row[0] = 1; row[1] = version; row[2] = ev_id;
                row[3] = batch_first;
                break;
            }
            case EV_SG_FAILED: case EV_SG_EXT_SIGNALED:
                if (slot >= 0 && slot < cap_sg)
                    clear_row(sg + slot * SG_N, SG_N);
                break;
            default:
                break;  // MarkerRecorded, UpsertSearchAttributes, etc.
            }
        }
    }
}

}  // extern "C"
