"""North-star benchmark: batched deep-history replay throughput.

Measures histories rebuilt per second at ~1k-event depth — the metric in
BASELINE.json ("histories replayed/sec/chip @1k-event depth"). One
device step = replay scan + vectorized task refresh, i.e. the full
rebuild semantics of the reference's nDCStateRebuilder.rebuild
(/root/reference/service/history/nDCStateRebuilder.go:92-160: replay all
batches, then taskRefresher.refreshTasks).

Baseline: the reference's per-workflow sequential loop. The Go toolchain
is not present in this image, so the recorded ``vs_baseline`` is the
speedup over this repo's host oracle (cadence_tpu/core/state_builder.py),
which implements the identical per-event transition semantics the Go
loop does (differential-tested), measured on the same histories on this
host's CPU. Go is typically ~10-50x faster than CPython on this kind of
branchy struct code, so divide by that factor for a Go-equivalent
estimate.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

if "--cpu" in sys.argv:
    # the axon plugin bootstrap rewrites JAX_PLATFORMS; pin via config
    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    from cadence_tpu.core.mutable_state import MutableState
    from cadence_tpu.core.state_builder import StateBuilder
    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.pack import PackedHistories, pack_histories
    from cadence_tpu.ops.refresh import refresh_tasks_device
    from cadence_tpu.ops.replay import replay_scan
    from cadence_tpu.testing.event_generator import HistoryFuzzer

    on_cpu = jax.default_backend() == "cpu"
    depth = 1000
    n_unique = 32
    batch = 512 if on_cpu else 8192
    iters = 2 if on_cpu else 8

    caps = S.Capacities(max_events=1024)
    fuzzer = HistoryFuzzer(seed=42, caps=caps)
    histories = [
        (f"wf-{i}", f"run-{i}", fuzzer.generate(target_events=depth, close_prob=0.0))
        for i in range(n_unique)
    ]
    packed = pack_histories(histories, caps=caps)

    # tile the unique histories up to the full batch
    reps = (batch + n_unique - 1) // n_unique
    events = np.tile(packed.events, (reps, 1, 1))[:batch]
    lengths = np.tile(packed.lengths, reps)[:batch]
    mean_depth = float(lengths.mean())

    events_tm = jnp.asarray(
        np.ascontiguousarray(np.transpose(events, (1, 0, 2)))
    )

    def step(state, ev_tm):
        final = replay_scan(state, ev_tm)
        return final, refresh_tasks_device(final)

    step_jit = jax.jit(step)

    # device-resident zero state, reused every iteration (step_jit does
    # not donate, so the buffer survives)
    state0 = jax.device_put(
        jax.tree_util.tree_map(jnp.asarray, S.empty_state(batch, caps))
    )
    state0 = jax.block_until_ready(state0)

    # warmup / compile
    out = step_jit(state0, events_tm)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    for _ in range(iters):
        out = step_jit(state0, events_tm)
    jax.block_until_ready(out)
    device_s = (time.perf_counter() - t0) / iters
    device_rate = batch / device_s

    # host-oracle baseline: same semantics, per-workflow sequential loop
    n_oracle = 4
    t0 = time.perf_counter()
    for i in range(n_oracle):
        wf_id, run_id, batches = histories[i % n_unique]
        ms = MutableState(domain_id="dom")
        sb = StateBuilder(ms, id_generator=lambda: "fixed")
        sb.apply_batches("dom", "req", wf_id, run_id, batches)
    oracle_s = (time.perf_counter() - t0) / n_oracle
    oracle_rate = 1.0 / oracle_s

    print(
        json.dumps(
            {
                "metric": f"histories_replayed_per_sec_at_{int(round(mean_depth))}ev_depth",
                "value": round(device_rate, 2),
                "unit": "histories/s",
                "vs_baseline": round(device_rate / oracle_rate, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
