"""North-star benchmark: batched history-replay throughput vs a compiled
host baseline, across the five BASELINE.md workload configurations.

One device step = replay scan + vectorized task refresh, i.e. the full
rebuild semantics of the reference's nDCStateRebuilder.rebuild
(/root/reference/service/history/nDCStateRebuilder.go:92-160: replay all
batches, then taskRefresher.refreshTasks).

Baseline: ``native.replay_sequential`` — the C++ (-O3) sequential
replayer in native/sidecar.cpp, one workflow and one event at a time
with bit-identical transition semantics (differential-tested in
tests/test_native_replayer.py). This is the compiled stand-in for the
reference's Go stateBuilder.applyEvents loop
(/root/reference/service/history/stateBuilder.go:112-613) — measured on
this host, on the same packed tensors, so ``vs_baseline`` compares the
same computation on the same data. If anything it is a *stronger*
baseline than Go, which replays into pointer-heavy structs and maps.

Timing discipline: ``jax.block_until_ready`` does not reliably
synchronize on this platform (axon tunnel), so every device timing
chains ``iters`` dependent kernel calls and then fetches a scalar
checksum that data-depends on the final state — the wall clock covers
exactly ``iters`` full executions, nothing hides in the async queue.

Two device kernels are reported side by side:
  xla     lax.scan over replay_step (ops/replay.py) — state carry
          round-trips HBM every step
  pallas  VMEM-resident-state kernel (ops/replay_pallas.py), fed the
          field-major event layout + host-precomputed presence masks
          from the C++ packer — bound by streaming the event tensor

The roofline column reports the effective HBM bandwidth implied by each
kernel's event+state traffic vs the measured copy bandwidth of this
chip (``streams_gbps`` / ``copy_bw_gbps``).

Workload configs (BASELINE.md / reference canary/const.go:64-84):
  echo        1k-class workflows, ~11-event histories
  signal      signal-heavy ragged histories
  timer_storm timer-fire-dominated streams
  retry_deep  ~1k-event activity-retry histories (the headline config)
  ndc_storm   mixed fuzzer histories + ICI snapshot exchange

Prints ONE JSON line: the headline metric (histories/s at ~1k-event
depth, vs_baseline against the C++ replayer) plus per-config numbers
under "configs".
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

# BENCH_SMOKE=1: tiny shapes for CI coverage of the harness itself
# (tests/test_bench_smoke.py) — minutes -> seconds, CPU-safe.
SMOKE = os.environ.get("BENCH_SMOKE") == "1"

if "--cpu" in sys.argv:
    # the axon plugin bootstrap rewrites JAX_PLATFORMS; pin via config
    jax.config.update("jax_platforms", "cpu")


_PROBE_CACHE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache",
    "backend_probe.json")


def _probe_backend(timeout_s: float = 120.0, ttl_s: float = 3600.0):
    """Touch the backend in a *subprocess* with a hard timeout.

    On this platform the tunnel can wedge so that ``jax.devices()`` hangs
    forever in a retry loop (never raises) — probing in-process would
    turn a dead tunnel into a dead benchmark. A successful probe is
    cached (``.jax_cache/backend_probe.json``, ``ttl_s``) so back-to-back
    bench invocations don't each pay the full cold-init wait; failures
    are never cached (a revived tunnel should be found on the next run).
    A cached accelerator result is still *revalidated* with a short
    bounded probe before it's trusted — a tunnel that died inside the
    TTL must downgrade to the flagged CPU fallback, not hang the first
    in-process JAX call until the watchdog fires. A healthy, already-
    initialized tunnel answers well inside the short bound; a stale
    entry is dropped and the full-timeout probe re-runs (a cold
    restart slower than the short bound must be re-found, not pinned
    to CPU for the rest of the TTL). Cached "cpu" needs no
    revalidation (nothing to wedge).

    Returns (platform_or_None, probe_status) where probe_status is one
    of "ok" / "cached" / "failed-or-timeout".
    """
    code = "import jax; print(jax.devices()[0].platform)"

    def _sub(t):
        try:
            return subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=t)
        except subprocess.TimeoutExpired:
            return None

    try:
        with open(_PROBE_CACHE) as f:
            cached = json.load(f)
        if time.time() - cached.get("ts", 0) < ttl_s and cached.get("platform"):
            plat = cached["platform"]
            if plat == "cpu":
                return plat, "cached"
            r = _sub(min(timeout_s, 20.0))
            if (r is not None and r.returncode == 0
                    and r.stdout.strip().splitlines()[-1:] == [plat]):
                return plat, "cached"
            # stale: the backend changed under the cache — died, or a
            # cold restart slower than the short bound. Drop the entry
            # and fall through to the full-timeout probe: a healthy-
            # but-cold accelerator must be re-found, not pinned to the
            # CPU fallback for the rest of the TTL.
            try:
                os.remove(_PROBE_CACHE)
            except OSError:
                pass
    except (OSError, ValueError):
        pass
    r = _sub(timeout_s)
    if r is None:
        return None, "failed-or-timeout"
    if r.returncode != 0:
        return None, "failed-or-timeout"
    plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
    if not plat:
        return None, "failed-or-timeout"
    try:
        os.makedirs(os.path.dirname(_PROBE_CACHE), exist_ok=True)
        with open(_PROBE_CACHE, "w") as f:
            json.dump({"platform": plat, "ts": time.time()}, f)
    except OSError:
        pass  # cache is best-effort; the probe result still stands
    return plat, "ok"


# ---- single-print guarantee + wall-clock watchdog -----------------------
# The driver records stdout; whatever happens (tunnel death mid-run,
# unbounded compile, crash) exactly one parseable JSON line must appear.
_PRINT_LOCK = threading.Lock()
_PRINTED = False
_PARTIAL: dict = {}


def _emit(obj) -> None:
    global _PRINTED
    with _PRINT_LOCK:
        if _PRINTED:
            return
        _PRINTED = True
        print(json.dumps(obj), flush=True)


def _fail_record(error: str) -> dict:
    """Shared shape for any non-success record (driver parses these keys)."""
    head = _PARTIAL.get("retry_deep") or {}
    return {
        "metric": "histories_replayed_per_sec_at_1k_depth",
        "value": head.get("histories_per_sec", 0),
        "unit": "histories/s",
        "vs_baseline": head.get("vs_baseline", 0),
        "error": error,
        "configs": dict(_PARTIAL),
    }


def _watchdog(wall_s: float) -> None:
    def fire():
        _emit(_fail_record(
            f"wall-clock watchdog fired after {wall_s:.0f}s "
            "(backend hung or compile unbounded)"))
        os._exit(0)
    t = threading.Timer(wall_s, fire)
    t.daemon = True
    t.start()

# persistent compile cache: the deep-scan kernels take minutes to
# compile on this host; cached binaries make reruns start in seconds
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _build_histories(config: str, n_unique: int, caps):
    from cadence_tpu.testing import workloads as W
    from cadence_tpu.testing.event_generator import HistoryFuzzer

    rng = random.Random(42)
    fz = HistoryFuzzer(seed=42, caps=caps)
    retry_depth, timer_depth, ndc_depth = (
        (40, 40, 40) if SMOKE else (1000, 400, 1000))
    out = []
    for i in range(n_unique):
        if config == "echo":
            b = W.echo_history()
        elif config == "signal":
            b = W.signal_history(rng)
        elif config == "timer_storm":
            b = W.timer_storm_history(rng, depth=timer_depth)
        elif config == "retry_deep":
            b = W.retry_deep_history(rng, depth=retry_depth)
        else:  # ndc_storm
            b = W.ndc_storm_history(fz, depth=ndc_depth)
        out.append((f"wf-{i}", f"run-{i}", b))
    return out


def _tile(packed, batch: int):
    """Tile a packed batch of uniques up to `batch` rows (batch-major)."""
    n = packed.events.shape[0]
    reps = (batch + n - 1) // n
    events = np.tile(packed.events, (reps, 1, 1))[:batch]
    lengths = np.tile(packed.lengths, reps)[:batch]
    return events, lengths


def _pack_tiled_lanes(histories, caps, lanes: int, lane_len: int):
    """Tile a small unique set into a full PackedLanes grid — the packed
    analogue of ``_tile``: pack each unique once (host packing cost stays
    O(uniques)), then fill every lane back-to-back, exactly the layout
    ops/pack.pack_lanes produces for a homogeneous stream."""
    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.pack import (
        PackedLanes, pack_histories, round_scan_len,
    )

    ph = pack_histories(histories, caps=caps)
    per = [
        np.asarray(ph.events[i, : ph.lengths[i]])
        for i in range(len(histories))
    ]
    t = round_scan_len(lane_len)
    events = np.zeros((lanes, t, S.EV_N), np.int32)
    events[:, :, S.EV_TYPE] = -1
    seg_end = np.zeros((lanes, t), bool)
    out_row = np.zeros((lanes, t), np.int32)
    lengths = []
    lane_segments = [[] for _ in range(lanes)]
    k = 0
    for ln in range(lanes):
        cur = 0
        while True:
            arr = per[k % len(per)]
            n = arr.shape[0]
            if cur + n > t:
                break
            events[ln, cur : cur + n] = arr
            seg_end[ln, cur + n - 1] = True
            out_row[ln, cur + n - 1] = len(lengths)
            lane_segments[ln].append((len(lengths), cur, cur + n))
            lengths.append(n)
            cur += n
            k += 1
    return PackedLanes(
        events=events, seg_end=seg_end, out_row=out_row,
        lengths=np.asarray(lengths, np.int32),
        side=[None] * len(lengths), caps=caps, epoch_s=ph.epoch_s,
        lane_segments=lane_segments,
    )


def _bench_config_packed(config: str, caps, lanes: int, lane_len: int,
                         iters: int, baseline_histories: int):
    """Lane-packed replay throughput (ragged time packing + depth
    bucketing): histories ride back-to-back in each lane, so the scan
    spends steps on real events instead of per-history padding —
    effective scan length per history is its own depth, not the batch
    max. The step body is statically specialized to the batch's event
    types (replay.type_signature). mixed_depth additionally splits the
    stream into depth buckets (ops/dispatch.depth_buckets semantics) so
    the 10% deep stragglers don't stretch the shallow lanes."""
    from cadence_tpu import native
    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.pack import pack_histories, round_scan_len
    from cadence_tpu.ops.refresh import refresh_tasks_device
    from cadence_tpu.ops.replay import (
        replay_scan, replay_scan_packed, type_signature,
    )
    from cadence_tpu.testing import workloads as W

    rng = random.Random(43)
    if config == "mixed_depth":
        sh_d, dp_d = (8, 40) if SMOKE else (16, 1000)
        shallow = [
            (f"wf-s{i}", f"run-s{i}", W.retry_deep_history(rng, depth=sh_d))
            for i in range(16)
        ]
        deep = [
            (f"wf-d{i}", f"run-d{i}", W.retry_deep_history(rng, depth=dp_d))
            for i in range(8)
        ]
        mean_sh = float(np.mean(
            [sum(len(b) for b in h[2]) for h in shallow]))
        mean_dp = float(np.mean(
            [sum(len(b) for b in h[2]) for h in deep]))
        # lane budget split for a 90/10 history mix: each class packs
        # its own depth-bucketed lanes
        share_d = 0.1 * mean_dp / (0.9 * mean_sh + 0.1 * mean_dp)
        lanes_d = max(1, round(lanes * share_d))
        lanes_s = max(1, lanes - lanes_d)
        packs = [
            _pack_tiled_lanes(shallow, caps, lanes_s, lane_len),
            _pack_tiled_lanes(deep, caps, lanes_d, lane_len),
        ]
        uniques = shallow + deep
        base_mix = (shallow, deep)
    else:  # echo
        uniques = _build_histories(config, 32, caps)
        packs = [_pack_tiled_lanes(uniques, caps, lanes, lane_len)]
        base_mix = None

    n_hist = sum(p.n_histories for p in packs)
    total_events = sum(p.total_events for p in packs)
    total_cells = sum(p.lanes * p.scan_len for p in packs)
    total_steps = sum(p.scan_len for p in packs)
    padding_frac = (total_cells - total_events) / max(total_events, 1)
    mean_depth = total_events / max(n_hist, 1)
    present = set()
    for p in packs:
        present.update(p.present_types)
    types = type_signature(present)

    arrays = []
    for p in packs:
        ev, seg, row = p.time_major()
        arrays.append((
            jnp.asarray(ev), jnp.asarray(seg), jnp.asarray(row),
            S.empty_state(round_scan_len(p.n_histories), caps),
        ))
    states0 = tuple(
        jax.device_put(jax.tree_util.tree_map(
            jnp.asarray, S.empty_state(p.lanes, caps)))
        for p in packs
    )

    def step(states):
        new_states, outs = [], []
        for st, (ev, seg, row, out0) in zip(states, arrays):
            out0j = jax.tree_util.tree_map(jnp.asarray, out0)
            st2, out = replay_scan_packed(
                st, out0j, ev, seg, row, types=types)
            new_states.append(st2)
            outs.append(refresh_tasks_device(out))
        return tuple(new_states), tuple(outs)

    step_j = jax.jit(step)
    dt, _ = _time_chained(step_j, states0, iters)
    rate = n_hist / dt
    results = {"xla_packed": {
        "histories_per_sec": round(rate, 2),
        "batch_rebuild_ms": round(dt * 1000, 3),
        "us_per_step": round(dt / total_steps * 1e6, 3),
        "scan_steps": total_steps,
    }}

    # per-dispatch latency distribution through the registry's
    # exponential-bucket histogram (utils/metrics.py): the headline
    # latency lines are Registry.timer_stats-backed p50/p99, the same
    # machinery the serving scopes report — not a bench-local avg/max
    from cadence_tpu.utils.metrics import Scope as _Scope

    lat = _Scope()
    st = states0
    for _ in range(max(8, iters * 2)):
        with lat.timer("batch_rebuild"):
            out = jax.block_until_ready(step_j(st))
        st = out[0]
    lat_stats = lat.registry.timer_stats("batch_rebuild")

    # ---- today's path on the same workload: one scan padded to the
    # deepest history — the number lane packing is judged against
    nb_u = min(512, n_hist)
    if base_mix is not None:
        sh, dp = base_mix
        n_dp = max(1, round(nb_u * 0.1))
        ev_s, len_s = _tile(pack_histories(sh, caps=caps), nb_u - n_dp)
        ev_d, len_d = _tile(pack_histories(dp, caps=caps), n_dp)
        events_u = np.concatenate([ev_s, ev_d], axis=0)
        lengths_u = np.concatenate([len_s, len_d])
    else:
        events_u, lengths_u = _tile(
            pack_histories(uniques, caps=caps), nb_u)
    ev_tm_u = jnp.asarray(
        np.ascontiguousarray(np.transpose(events_u, (1, 0, 2))))
    state_u = jax.device_put(jax.tree_util.tree_map(
        jnp.asarray, S.empty_state(nb_u, caps)))

    def step_u(state):
        final = replay_scan(state, ev_tm_u)
        return final, refresh_tasks_device(final)

    dt_u, _ = _time_chained(jax.jit(step_u), state_u, max(2, iters // 2))
    unpacked_rate = nb_u / dt_u
    padding_u = (
        events_u.shape[0] * events_u.shape[1] - lengths_u.sum()
    ) / max(int(lengths_u.sum()), 1)

    # ---- compiled-host baseline on the same histories
    class _Sub:
        pass

    sub = _Sub()
    nb = min(baseline_histories, n_hist)
    if base_mix is not None:
        n_dp = max(1, round(nb * 0.1))
        ev_s, len_s = _tile(pack_histories(sh, caps=caps), nb - n_dp)
        ev_d, len_d = _tile(pack_histories(dp, caps=caps), n_dp)
        sub.events = np.concatenate([ev_s, ev_d], axis=0)
        sub.lengths = np.concatenate([len_s, len_d])
    else:
        sub.events, sub.lengths = _tile(
            pack_histories(uniques, caps=caps), nb)
    sub.caps = caps
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < 0.5:
        native.replay_sequential(sub)
        reps += 1
    cpp_rate = nb / ((time.perf_counter() - t0) / reps)

    return {
        "histories_per_sec": round(rate, 2),
        "kernel": "xla_packed",
        "packed": True,
        "baseline_cpp_per_sec": round(cpp_rate, 2),
        "vs_baseline": round(rate / cpp_rate, 2),
        "mean_depth": round(mean_depth, 1),
        "batch_rebuild_ms": round(dt * 1000, 3),
        "latency_p50_ms": round(lat_stats.p50 * 1e3, 3),
        "latency_p99_ms": round(lat_stats.p99 * 1e3, 3),
        "batch": n_hist,
        "lanes": sum(p.lanes for p in packs),
        "buckets": len(packs),
        "padding_frac": round(padding_frac, 4),
        "lanes_per_history": round(
            sum(p.lanes for p in packs) / max(n_hist, 1), 4),
        "unpacked_histories_per_sec": round(unpacked_rate, 2),
        "unpacked_padding_frac": round(float(padding_u), 4),
        "vs_unpacked": round(rate / unpacked_rate, 2),
        "kernels": results,
    }


def _bench_reshard_live(duration_s: float, load_threads: int = 2,
                        probe_interval_s: float = 0.004):
    """Elastic resharding under sustained load: a shard split executed
    mid-run while load threads drive the echo workflow end-to-end and a
    probe thread times every frontend start call (the routed write path
    — exactly what stalls while the source shard is fenced).

    Reports the steady-state completion rate next to the handoff
    record: total ``handoff_ms`` (dominated by the pre-fence checkpoint
    flush, which runs under live traffic), the write-unavailability
    ``pause_ms``, and the probe-call p50/p99 — overall and within the
    handoff window, the decision-latency cost of the reconfiguration.
    """
    import threading as _threading

    from cadence_tpu.runtime.api import StartWorkflowRequest
    from cadence_tpu.runtime.resharding import ReshardCoordinator
    from cadence_tpu.testing.onebox import Onebox
    from cadence_tpu.worker import Worker

    box = Onebox(num_shards=2, checkpoints=True,
                 start_worker=False).start()
    box.domain_handler.register_domain("bench")

    def _echo_wf(ctx, input):
        out = yield ctx.schedule_activity("echo", input)
        return out

    w = Worker(box.frontend, "bench", "bench-tl", identity="bench-w",
               sticky=False)
    w.register_workflow("echo-wf", _echo_wf)
    w.register_activity("echo", lambda x: x)
    w.start()

    stop = _threading.Event()
    completed = [0]
    lock = _threading.Lock()

    def _start(wid):
        return box.frontend.start_workflow_execution(StartWorkflowRequest(
            domain="bench", workflow_id=wid, workflow_type="echo-wf",
            task_list="bench-tl", input=b"x", request_id=f"req-{wid}",
            execution_start_to_close_timeout_seconds=60,
        ))

    def _load(tid):
        i = 0
        while not stop.is_set():
            wid = f"load-{tid}-{i}"
            try:
                rid = _start(wid)
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline and not stop.is_set():
                    d = box.frontend.describe_workflow_execution(
                        "bench", wid, rid
                    )
                    if not d.is_running:
                        with lock:
                            completed[0] += 1
                        break
                    time.sleep(0.002)
            except Exception:
                pass  # fenced-window stragglers: the probe counts those
            i += 1

    probes = []  # (t_monotonic, latency_s)

    def _probe():
        j = 0
        while not stop.is_set():
            t0 = time.monotonic()
            try:
                _start(f"probe-{j}")
            except Exception:
                pass
            probes.append((t0, time.monotonic() - t0))
            j += 1
            time.sleep(probe_interval_s)

    threads = [
        _threading.Thread(target=_load, args=(t,), daemon=True)
        for t in range(load_threads)
    ] + [_threading.Thread(target=_probe, daemon=True)]
    t_run0 = time.monotonic()
    try:
        for t in threads:
            t.start()
        time.sleep(duration_s / 2)

        coord = ReshardCoordinator(
            box.persistence, [box.history.controller]
        )
        t_h0 = time.monotonic()
        plan = coord.split(0)
        t_h1 = time.monotonic()

        time.sleep(duration_s / 2)
    finally:
        # a failed split must not leak live pumps into later configs
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        elapsed = time.monotonic() - t_run0
        w.stop()
        box.stop()

    # headline percentiles through Registry.timer_stats — the same
    # exponential-bucket histograms the serving scopes report, not a
    # bench-local sorted-list estimator (utils/metrics.py)
    from cadence_tpu.utils.metrics import Scope as _Scope

    lat_scope = _Scope()
    lat_handoff = []
    for t0, dt in probes:
        lat_scope.record("start_latency", dt)
        if t_h0 <= t0 <= t_h1:
            lat_scope.tagged(window="handoff").record(
                "start_latency_handoff", dt
            )
            lat_handoff.append(dt)
    reg = lat_scope.registry
    lat_all_stats = reg.timer_stats("start_latency")
    lat_handoff_stats = reg.timer_stats("start_latency_handoff")
    return {
        "steady_rate_wf_per_sec": round(completed[0] / elapsed, 2),
        "workflows_completed": completed[0],
        "probe_calls": len(probes),
        "start_p50_ms": round(lat_all_stats.p50 * 1e3, 3),
        "start_p99_ms": round(lat_all_stats.p99 * 1e3, 3),
        "during_handoff": {
            "samples": len(lat_handoff),
            "p50_ms": round(lat_handoff_stats.p50 * 1e3, 3),
            "p99_ms": round(lat_handoff_stats.p99 * 1e3, 3),
            "max_ms": round(max(lat_handoff, default=0.0) * 1e3, 3),
        },
        "handoff": {
            "state": plan.state,
            "epoch": plan.epoch_to,
            "handoff_ms": round(plan.handoff_ms, 1),
            "pause_ms": round(plan.pause_ms, 1),
            "moved_workflows": plan.moved_workflows,
            "moved_tasks": plan.moved_tasks,
            "checkpoints_shipped": plan.checkpoints_shipped,
            "suffix_events_replayed": plan.suffix_events_replayed,
        },
    }


def _bench_replication_lag(workflows: int, signals_each: int,
                           bytes_per_s: float, payload: int = 96):
    """Geo-replication catch-up under a throttled WAN link: event-ship
    vs snapshot-ship vs adaptive (runtime/replication/transport.py).

    Per arm, a fresh two-cluster pair: the active side accumulates a
    replication backlog (starts + signals, no worker — every write
    mints a replication task), then the standby drains it through a
    seeded ``SimulatedLink`` with a ``bytes_per_s`` budget.

      events    the pre-adaptive pull plane (no transport): the full
                hydrated event backlog pages over the throttled link
      snapshot  mode controller pinned to snapshot shipping: one
                backlog probe, then per-run delta-compressed
                ReplayCheckpoints + deferred history backfill
      adaptive  the controller decides per measured budget (the
                mode-switch count proves it actually switched)

    ``catch_up_s`` is time-to-state-current (every standby run's state
    tip matches the active tip — what failover readiness means);
    ``converged_s`` additionally drains the history backfill debt so
    the standby is byte-identical. For the events arm the two
    coincide. ``events_replayed_saved`` on the snapshot arms proves the
    suffix-only resume path carried the installs.
    """
    import uuid as _uuid

    from cadence_tpu.client import HistoryClient, MatchingClient
    from cadence_tpu.cluster import ClusterInformation, ClusterMetadata
    from cadence_tpu.matching import MatchingEngine
    from cadence_tpu.runtime.api import SignalRequest, StartWorkflowRequest
    from cadence_tpu.runtime.domains import DomainCache, register_domain
    from cadence_tpu.runtime.membership import single_host_monitor
    from cadence_tpu.runtime.persistence.memory import create_memory_bundle
    from cadence_tpu.runtime.replication import (
        AdaptiveTransport,
        HistoryRereplicator,
        ReplicationTaskFetcher,
        ReplicationTaskProcessor,
    )
    from cadence_tpu.runtime.service import HistoryService
    from cadence_tpu.testing.faults import LinkProfile, chaos_link
    from cadence_tpu.utils.metrics import Scope

    DOMAIN = "repl-bench"

    def make_cluster(name, domain_id, metrics=None):
        meta = ClusterMetadata(
            failover_version_increment=10,
            master_cluster_name="active", current_cluster_name=name,
            cluster_info={
                "active": ClusterInformation(initial_failover_version=1),
                "standby": ClusterInformation(initial_failover_version=2),
            },
        )
        persistence = create_memory_bundle()
        register_domain(
            persistence.metadata, DOMAIN, is_global=True,
            clusters=["active", "standby"], active_cluster="active",
            domain_id=domain_id, failover_version=1,
        )
        domains = DomainCache(persistence.metadata)
        svc = HistoryService(
            1, persistence, domains, single_host_monitor(f"{name}-h"),
            cluster_metadata=meta, metrics=metrics,
        )
        hc = HistoryClient(svc.controller)
        matching = MatchingEngine(persistence.task, hc)
        svc.wire(MatchingClient(matching), hc)
        svc.start()
        return {"svc": svc, "hc": hc, "matching": matching,
                "persistence": persistence, "domain_id": domain_id}

    class Adapter:
        def __init__(self, svc):
            self.svc = svc

        def get_replication_messages(self, shard_id, last, max_tasks=None):
            return self.svc.get_replication_messages(
                shard_id, last, cluster="standby", max_tasks=max_tasks)

        def get_workflow_history_raw(self, *a):
            return self.svc.get_workflow_history_raw(*a)

        def get_replication_backlog(self, shard_id, last):
            return self.svc.get_replication_backlog(shard_id, last)

        def get_replication_checkpoint(self, *a):
            return self.svc.get_replication_checkpoint(*a)

    def run_arm(arm):
        domain_id = str(_uuid.uuid4())
        scope = Scope()
        active = make_cluster("active", domain_id)
        # one registry for the whole standby side: the transport's
        # counters and the rebuilder's events_replayed_saved must land
        # together for the record to read coherently
        standby = make_cluster("standby", domain_id, metrics=scope)
        runs = {}
        try:
            for i in range(workflows):
                wid = f"lag-wf-{i}"
                rid = active["hc"].start_workflow_execution(
                    StartWorkflowRequest(
                        domain=DOMAIN, workflow_id=wid,
                        workflow_type="echo", task_list="tl",
                        request_id=f"req-{wid}",
                        execution_start_to_close_timeout_seconds=600,
                    ))
                for k in range(signals_each):
                    active["hc"].signal_workflow_execution(SignalRequest(
                        domain=DOMAIN, workflow_id=wid,
                        signal_name=f"s{k}", input=b"x" * payload,
                        identity="bench",
                    ))
                runs[wid] = rid
            tips = {}
            total_events = 0
            for wid, rid in runs.items():
                resp = active["persistence"].execution.\
                    get_workflow_execution(0, domain_id, wid, rid)
                tips[wid] = resp.next_event_id - 1
                total_events += tips[wid]
            # small fetch pages: the first page is the link probe, not
            # the whole hydrated backlog in one transfer
            emit = active["svc"].controller.get_engine_for_shard(0)\
                .replicator_queue
            emit.batch_size = 8
            if arm != "events":
                # absorb the snapshot-serving compile (rebuild_many
                # device path) outside the timed window, exactly the
                # warm-up discipline every other config applies
                wid0 = next(iter(runs))
                active["svc"].get_replication_checkpoint(
                    domain_id, wid0, runs[wid0])

            link = chaos_link(
                Adapter(active["svc"]),
                LinkProfile(bytes_per_s=bytes_per_s), seed=7,
            )
            fetcher = ReplicationTaskFetcher("active", link)
            engine = standby["svc"].controller.get_engine_for_shard(0)
            transport = None
            if arm != "events":
                transport = AdaptiveTransport(
                    link, "active",
                    min_gap_events=8, min_dwell=1,
                    snapshot_bytes_prior=4096,
                    force_mode=("snapshot" if arm == "snapshot" else None),
                    metrics=scope,
                )
            rerepl = HistoryRereplicator(
                link, engine.ndc_replicator, transport=transport,
                metrics=scope,
            )
            proc = ReplicationTaskProcessor(
                engine.shard, engine.ndc_replicator, fetcher,
                rereplicator=rerepl, metrics=scope, transport=transport,
            )

            def state_current():
                ex = standby["persistence"].execution
                for wid, rid in runs.items():
                    try:
                        resp = ex.get_workflow_execution(
                            0, domain_id, wid, rid)
                    except Exception:
                        return False
                    if resp.next_event_id - 1 < tips[wid]:
                        return False
                return True

            t0 = time.monotonic()
            catch_up_s = None
            deadline = t0 + 300.0
            while time.monotonic() < deadline:
                n = proc.process_once()
                if catch_up_s is None and state_current():
                    catch_up_s = time.monotonic() - t0
                if n == 0 and catch_up_s is not None:
                    break
            converged_s = time.monotonic() - t0
            # byte-parity sanity: every replicated event landed
            standby_events = 0
            for wid, rid in runs.items():
                ev, _ = engine.get_workflow_execution_history(
                    DOMAIN, wid, rid)
                standby_events += len(ev)
            reg = scope.registry
            return {
                "catch_up_s": round(catch_up_s or converged_s, 3),
                "converged_s": round(converged_s, 3),
                "bytes_shipped": link.link.bytes_total,
                "backlog_events": total_events,
                "converged": standby_events == total_events,
                "mode_switches": (
                    transport.controller.switches if transport else 0
                ),
                "snapshots_shipped": reg.counter_value(
                    "replication_snapshots_shipped"),
                "events_replayed_saved": reg.counter_value(
                    "events_replayed_saved"),
            }
        finally:
            standby["svc"].stop()
            standby["matching"].shutdown()
            active["svc"].stop()
            active["matching"].shutdown()

    out = {}
    for arm in ("events", "snapshot", "adaptive"):
        out[arm] = run_arm(arm)
    ev, ad = out["events"], out["adaptive"]
    out["adaptive_vs_events"] = round(
        ad["catch_up_s"] / max(ev["catch_up_s"], 1e-9), 3
    )
    out["link_bytes_per_s"] = bytes_per_s
    return out


def _bench_failover_drill(workflows: int, signals_each: int,
                          bytes_per_s: float,
                          unavailability_slo_ms: float = 5000.0,
                          payload: int = 96):
    """Domain failover drills over a throttled WAN link
    (runtime/replication/failover.py; README "Domain failover").

    One two-cluster pair runs all three drill shapes in sequence:

      managed   graceful handover active->standby: the handover pays
                the backlog catch-up through the throttled link before
                the flip (handover_ms), with a metadata-only
                unavailability window (unavailability_ms) and a
                drained link at promote time (lag 0)
      forced    region loss: the link partitions with divergent events
                outstanding on the now-active side, the survivor is
                promoted blind (unavailability_ms = flip->observed)
      failback  the recovered region re-syncs, the version-branch
                storm resolves (conflicts_resolved — the NDC
                rebuild-at-LCA path), and ownership returns home

    The ``slo`` block is the contract the smoke test pins: every
    drill's unavailability window inside ``unavailability_slo_ms``,
    at least one conflict actually resolved, and zero replication lag
    after the final convergence.
    """
    import uuid as _uuid

    from cadence_tpu.client import HistoryClient, MatchingClient
    from cadence_tpu.cluster import ClusterInformation, ClusterMetadata
    from cadence_tpu.matching import MatchingEngine
    from cadence_tpu.runtime.api import SignalRequest, StartWorkflowRequest
    from cadence_tpu.runtime.domains import DomainCache, register_domain
    from cadence_tpu.runtime.membership import single_host_monitor
    from cadence_tpu.runtime.persistence.memory import create_memory_bundle
    from cadence_tpu.runtime.replication import (
        AdaptiveTransport,
        ClusterHandle,
        DomainFailoverCoordinator,
        HistoryRereplicator,
        ReplicationTaskFetcher,
        ReplicationTaskProcessor,
    )
    from cadence_tpu.runtime.service import HistoryService
    from cadence_tpu.testing.faults import (
        LinkPartitionedError,
        LinkProfile,
        chaos_link,
    )
    from cadence_tpu.utils.metrics import Scope

    DOMAIN = "fo-bench"
    domain_id = str(_uuid.uuid4())

    def meta(name):
        return ClusterMetadata(
            failover_version_increment=10,
            master_cluster_name="active", current_cluster_name=name,
            cluster_info={
                "active": ClusterInformation(initial_failover_version=1),
                "standby": ClusterInformation(initial_failover_version=2),
            },
        )

    def make_cluster(name):
        scope = Scope()
        persistence = create_memory_bundle()
        register_domain(
            persistence.metadata, DOMAIN, is_global=True,
            clusters=["active", "standby"], active_cluster="active",
            domain_id=domain_id, failover_version=1,
        )
        domains = DomainCache(persistence.metadata)
        svc = HistoryService(
            1, persistence, domains, single_host_monitor(f"fo-{name}"),
            cluster_metadata=meta(name), metrics=scope,
        )
        hc = HistoryClient(svc.controller)
        matching = MatchingEngine(persistence.task, hc)
        svc.wire(MatchingClient(matching), hc)
        svc.start()
        svc.controller.get_engine_for_shard(0)\
            .replicator_queue.batch_size = 8
        return {"svc": svc, "hc": hc, "matching": matching,
                "persistence": persistence, "domains": domains,
                "scope": scope}

    class Adapter:
        def __init__(self, svc, consumer):
            self.svc = svc
            self.consumer = consumer

        def get_replication_messages(self, shard_id, last, max_tasks=None):
            return self.svc.get_replication_messages(
                shard_id, last, cluster=self.consumer,
                max_tasks=max_tasks)

        def get_workflow_history_raw(self, *a):
            return self.svc.get_workflow_history_raw(*a)

        def get_replication_backlog(self, shard_id, last):
            return self.svc.get_replication_backlog(shard_id, last)

        def get_replication_checkpoint(self, *a):
            return self.svc.get_replication_checkpoint(*a)

    clusters = {n: make_cluster(n) for n in ("active", "standby")}
    links, processors = {}, {}
    for consumer, source in (("standby", "active"), ("active", "standby")):
        wrapped = chaos_link(
            Adapter(clusters[source]["svc"], consumer),
            LinkProfile(bytes_per_s=bytes_per_s, max_sleep_s=1.0),
            seed=7,
        )
        links[consumer] = wrapped.link
        engine = clusters[consumer]["svc"].controller\
            .get_engine_for_shard(0)
        transport = AdaptiveTransport(
            wrapped, source, min_gap_events=1 << 30,
            metrics=clusters[consumer]["scope"],
        )
        rerepl = HistoryRereplicator(
            wrapped, engine.ndc_replicator, transport=transport,
            metrics=clusters[consumer]["scope"],
        )
        processors[consumer] = ReplicationTaskProcessor(
            engine.shard, engine.ndc_replicator,
            ReplicationTaskFetcher(source, wrapped),
            rereplicator=rerepl,
            metrics=clusters[consumer]["scope"], transport=transport,
        )
        clusters[consumer]["transport"] = transport

    fo_scope = Scope()
    coordinator = DomainFailoverCoordinator(
        meta("active"),
        [ClusterHandle(
            name=n, metadata=clusters[n]["persistence"].metadata,
            domains=clusters[n]["domains"], history=clusters[n]["svc"],
            processors=[processors[n]],
            transport=clusters[n].get("transport"),
            registry=clusters[n]["scope"].registry,
        ) for n in ("active", "standby")],
        metrics=fo_scope, drain_timeout_s=240.0,
    )
    retryable = (LinkPartitionedError,)

    def signal(cluster, wid, name):
        clusters[cluster]["hc"].signal_workflow_execution(SignalRequest(
            domain=DOMAIN, workflow_id=wid, signal_name=name,
            input=b"x" * payload, identity="fo-bench",
        ))

    try:
        # backlog on the home region
        wids = [f"fo-wf-{i}" for i in range(workflows)]
        for wid in wids:
            clusters["active"]["hc"].start_workflow_execution(
                StartWorkflowRequest(
                    domain=DOMAIN, workflow_id=wid, workflow_type="echo",
                    task_list="fo-tl", request_id=f"req-{wid}",
                    execution_start_to_close_timeout_seconds=600,
                ))
            for k in range(signals_each):
                signal("active", wid, f"s{k}")

        # drill 1: managed handover pays the backlog catch-up
        r_managed = coordinator.managed_handover(DOMAIN, "standby")

        # drill 2: divergence on the new active side, then region loss
        coordinator.await_convergence(DOMAIN, swallow=retryable)
        for wid in wids:
            signal("standby", wid, "orphan")
        for link in links.values():
            link.force_partition(True)
        t_loss = time.monotonic()
        r_forced = coordinator.forced_failover(
            DOMAIN, "active", lost_clusters=["standby"]
        )
        detect_to_promote_ms = (time.monotonic() - t_loss) * 1000.0
        for wid in wids:
            signal("active", wid, "promoted")

        # drill 3: the lost region recovers; storm resolves; failback
        for link in links.values():
            link.force_partition(False)
        t_heal = time.monotonic()
        r_failback = coordinator.failback(
            DOMAIN, "standby", swallow=retryable
        )
        converged_s = time.monotonic() - t_heal
        lag_final = max(
            int(c["transport"].estimator.lag_events)
            for c in clusters.values() if "transport" in c
        )

        def row(r, extra=None):
            d = {
                "handover_ms": round(r.handover_ms, 2),
                "unavailability_ms": round(r.unavailability_ms, 2),
                "lag_at_promote_events": r.replication_lag_at_promote,
                "conflicts_resolved": r.conflicts_resolved,
            }
            if extra:
                d.update(extra)
            return d

        unavail = [r_managed.unavailability_ms,
                   r_forced.unavailability_ms,
                   r_failback.unavailability_ms]
        return {
            "managed": row(r_managed,
                           {"drained_tasks": r_managed.drained_tasks}),
            "forced": row(r_forced, {
                "detect_to_promote_ms": round(detect_to_promote_ms, 2),
            }),
            "failback": row(r_failback, {
                "converged_s": round(converged_s, 3),
            }),
            "slo": {
                "unavailability_ms_bound": unavailability_slo_ms,
                "unavailability_ms_worst": round(max(unavail), 2),
                "met": bool(
                    max(unavail) < unavailability_slo_ms
                    and r_failback.conflicts_resolved >= 1
                    and lag_final == 0
                ),
            },
            "conflicts_resolved_total": r_failback.conflicts_resolved,
            "replication_lag_events_final": lag_final,
            "link_bytes_per_s": bytes_per_s,
            "bytes_shipped": sum(l.bytes_total for l in links.values()),
        }
    finally:
        for c in clusters.values():
            c["svc"].stop()
            c["matching"].shutdown()


def _bench_rebuild_warm(n_hist: int, depth: int, iters: int,
                        tail_frac: float = 0.125):
    """Checkpointed incremental replay: rebuild the same cohort twice.

    Builds ``n_hist`` retry_deep-shaped runs in a memory history store,
    seeds checkpoints at ~(1 - tail_frac) of each history (an untimed
    rebuild of the prefix), appends the tails, then times two full
    rebuild_many passes over identical requests: COLD (no checkpoint
    manager — replay from event 1) and WARM (resume from the prefix
    snapshots — replay only the tail). Both passes run the complete
    pipeline (history read, pack, device scan, MutableState rehydrate,
    task refresh), so the ratio is the end-to-end win of converting
    repeat-rebuild cost from O(depth) to O(new events).

    ``suffix_frac`` = events actually replayed on the warm pass ÷ total
    events; ``checkpoint_hit_rate`` from the warm rebuilder's counters.
    """
    import random as _random

    from cadence_tpu.checkpoint import CheckpointManager, CheckpointPolicy
    from cadence_tpu.runtime.persistence.memory import create_memory_bundle
    from cadence_tpu.runtime.replication.rebuilder import (
        RebuildRequest,
        StateRebuilder,
    )
    from cadence_tpu.testing import workloads as W
    from cadence_tpu.utils.metrics import Scope

    rng = _random.Random(45)
    bundle = create_memory_bundle()
    history = bundle.history

    reqs = []
    prefixes, tails = [], []
    total_events = 0
    suffix_events = 0
    for i in range(n_hist):
        batches = W.retry_deep_history(rng, depth=depth)
        n_events = sum(len(b) for b in batches)
        cut_events = int(n_events * (1.0 - tail_frac))
        cut, seen = len(batches), 0
        for k, b in enumerate(batches):
            if seen + len(b) > cut_events:
                cut = max(k, 1)  # keep at least the start batch
                break
            seen += len(b)
        prefix, tail = batches[:cut], batches[cut:]
        total_events += n_events
        suffix_events += sum(len(b) for b in tail)
        branch = history.new_history_branch(tree_id=f"run-{i}")
        txn = 1
        for b in prefix:
            history.append_history_nodes(branch, b, transaction_id=txn)
            txn += 1
        prefixes.append(txn)
        tails.append((branch, tail))
        reqs.append(RebuildRequest(
            domain_id="dom", workflow_id=f"wf-{i}", run_id=f"run-{i}",
            branch_token=branch.to_json().encode(),
        ))

    # seed: untimed prefix rebuild writes the checkpoints the warm pass
    # resumes from (every_events=1 → always write; keep_last=1 floors
    # the store at one snapshot per run)
    mgr = CheckpointManager(
        bundle.checkpoint, CheckpointPolicy(every_events=1, keep_last=1)
    )
    StateRebuilder(history, checkpoints=mgr).rebuild_many(reqs)
    for (branch, tail), txn in zip(tails, prefixes):
        for b in tail:
            history.append_history_nodes(branch, b, transaction_id=txn)
            txn += 1

    def _timed(rebuilder, lat_scope=None):
        # warm-up run first: jit compiles (each pass's scan shapes and
        # the resume-variant kernel differ) must not masquerade as
        # replay cost — same discipline as _time_chained elsewhere.
        # ``lat_scope`` additionally records each pass into a registry
        # histogram timer (the p50/p99 the record reports are
        # Registry.timer_stats-backed, like the serving scopes)
        rebuilder.rebuild_many(reqs)
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            if lat_scope is not None:
                with lat_scope.timer("rebuild_many"):
                    out = rebuilder.rebuild_many(reqs)
            else:
                out = rebuilder.rebuild_many(reqs)
        dt = (time.perf_counter() - t0) / iters
        assert all(r is not None for r in out)
        return dt

    cold_dt = _timed(StateRebuilder(history))
    # a huge every_events keeps the warm pass read-only on the store
    # (the tail advance is below the write threshold)
    warm_metrics = Scope()
    warm_mgr = CheckpointManager(
        bundle.checkpoint,
        CheckpointPolicy(every_events=1 << 30, keep_last=1),
    )
    warm_lat = Scope()
    warm_dt = _timed(StateRebuilder(
        history, checkpoints=warm_mgr, metrics=warm_metrics,
    ), lat_scope=warm_lat)
    warm_stats = warm_lat.registry.timer_stats("rebuild_many")

    reg = warm_metrics.registry
    hits = reg.counter_value("checkpoint_hit")
    lookups = (
        hits
        + reg.counter_value("checkpoint_miss")
        + reg.counter_value("checkpoint_invalidated")
    )
    warm_rate = n_hist / warm_dt
    cold_rate = n_hist / cold_dt
    # the warm-up pass inside _timed does the same lookups as each
    # timed pass, so the counters hold (iters + 1) identical passes
    saved_per_pass = int(
        reg.counter_value("events_replayed_saved") // (iters + 1)
    )
    return {
        "histories_per_sec": round(warm_rate, 2),
        "kernel": "rebuild_many",
        "cold_histories_per_sec": round(cold_rate, 2),
        "vs_cold": round(warm_rate / cold_rate, 2),
        "checkpoint_hit_rate": round(hits / max(lookups, 1), 4),
        # MEASURED from the warm counters (not the workload's configured
        # cut): a resume regression that silently replays full histories
        # pushes this back toward 1.0 even while lookups still hit
        "suffix_frac": round(
            1.0 - saved_per_pass / max(total_events, 1), 4
        ),
        "suffix_frac_configured": round(
            suffix_events / max(total_events, 1), 4
        ),
        "events_replayed_saved": saved_per_pass,
        "mean_depth": round(total_events / max(n_hist, 1), 1),
        "batch": n_hist,
        "batch_rebuild_ms": round(warm_dt * 1000, 3),
        "latency_p50_ms": round(warm_stats.p50 * 1e3, 3),
        "latency_p99_ms": round(warm_stats.p99 * 1e3, 3),
        "cold_batch_rebuild_ms": round(cold_dt * 1000, 3),
    }


def _bench_serve_continuous(workflows: int, qps: float, lanes: int = 64,
                            prefix_frac: float = 0.4,
                            min_events: int = 60, max_events: int = 400,
                            delta_batches: int = 3,
                            kind: str = "poisson"):
    """Continuous-batching serving under open-loop load.

    Builds ``workflows`` signal-dominated OPEN histories, seats a
    prefix of each into the resident engine (cadence_tpu/serving/), and
    drives the remaining batches as per-arrival Δ appends on an
    open-loop schedule (``kind``: poisson | bursty) at sustained
    ``qps`` through a token bucket. Every request's decision latency is
    measured from its SCHEDULED arrival to the resident read — falling
    behind shows up as queueing delay in the p99, exactly as it would
    for real users (closed-loop benches hide this).

    The O(Δ) proof is ``suffix_frac``: events the engine actually
    composed across all appends ÷ events a cold per-arrival rebuild of
    the same cohort would have replayed (each arrival re-replaying its
    full prefix). ``events_per_append`` ≈ the mean Δ width — resident
    appends never pay O(depth). p50/p99 come from the PR 9
    exponential-bucket histograms (``Registry.timer_stats``), the same
    plane production scrapes.
    """
    import random as _random

    from cadence_tpu.ops import schema as S
    from cadence_tpu.serving import (
        ArrivalProcess,
        OpenLoopHarness,
        ResidentEngine,
        ServeWorkload,
    )
    from cadence_tpu.testing import workloads as W
    from cadence_tpu.utils.metrics import Scope
    from cadence_tpu.utils.quotas import TokenBucket

    caps = S.Capacities(
        max_events=512, max_activities=2, max_timers=2,
        max_children=2, max_request_cancels=2, max_signals_ext=4,
        max_version_items=2)

    def build(tag):
        # same seed per call: the warm round sees IDENTICAL history
        # shapes (and therefore identical jit keys) as the timed round
        rng = _random.Random(46)
        loads, cold_events, appended_events = [], 0, 0
        for i in range(workflows):
            batches = W.signal_history(
                rng, min_events=min_events, max_events=max_events)
            cut = max(1, int(len(batches) * prefix_frac))
            deltas = [
                batches[k : k + delta_batches]
                for k in range(cut, len(batches), delta_batches)
            ]
            seen = sum(len(b) for b in batches[:cut])
            for d in deltas:
                dn = sum(len(b) for b in d)
                seen += dn
                appended_events += dn
                cold_events += seen  # cold replays the full prefix
            loads.append(ServeWorkload(
                domain_id="bench", workflow_id=f"serve-{tag}-wf-{i}",
                run_id=f"serve-{tag}-run-{i}", branch_token=b"",
                prefix=batches[:cut], deltas=deltas,
            ))
        return loads, cold_events, appended_events

    def drive(tag, scope):
        loads, cold_events, appended_events = build(tag)
        engine = ResidentEngine(lanes=lanes, caps=caps, metrics=scope)
        harness = OpenLoopHarness(
            engine, loads,
            ArrivalProcess(qps=qps, kind=kind, seed=7),
            metrics=scope,
            # the admission token bucket: sized above the target rate
            # so steady state admits, but a burst beyond 2x qps sheds
            # load instead of queueing it into the p99
            admission_bucket=TokenBucket(
                rps=qps * 2.0, burst=max(8, int(qps))),
        )
        run = harness.run()
        return loads, cold_events, appended_events, run, engine

    # warm round first (untimed, own registry): jit compiles of the
    # tick/seat shapes must not masquerade as open-loop queueing delay
    # — same discipline as _time_chained / _bench_rebuild_warm
    from cadence_tpu.utils.metrics import NOOP as _NOOP

    drive("warm", _NOOP)[4].drain()
    scope = Scope()
    reg = scope.registry
    loads, cold_events, appended_events, run, engine = drive(
        "run", scope)
    drained = engine.drain()

    # cold comparison cohort: ONE batched rebuild of the final
    # histories — context for what the resident plane displaced
    from cadence_tpu.ops.dispatch import replay_stream

    full = [
        (w.workflow_id, w.run_id,
         list(w.prefix) + [b for d in w.deltas for b in d])
        for w in loads
    ]
    t0 = time.perf_counter()
    replay_stream(full, caps=caps, lane_pack=True)
    cold_cohort_ms = (time.perf_counter() - t0) * 1000
    total_events = sum(
        sum(len(b) for b in batches) for _, _, batches in full
    )

    stats = reg.timer_stats("serve_decision")
    hits = reg.counter_value("serving_resident_hits")
    misses = reg.counter_value("serving_cold_misses")
    appends = reg.counter_value("serving_appends")
    replayed = reg.counter_value("serving_events_replayed")
    ticks = reg.counter_value("serving_ticks")
    return {
        "arrival": kind,
        "workflows": workflows,
        "lanes": lanes,
        "requests": run["requests"],
        "completed": run["completed"],
        "shed": run["shed"],
        "qps_target": round(run["qps_target"], 1),
        "qps_sustained": round(run["qps_sustained"], 1),
        "wall_s": round(run["wall_s"], 3),
        # the SLO block: open-loop decision latency (scheduled arrival
        # -> resident read done) off the histogram plane
        "latency_p50_ms": round(stats.p50 * 1e3, 3),
        "latency_p99_ms": round(stats.p99 * 1e3, 3),
        "resident_hit_rate": round(hits / max(hits + misses, 1), 4),
        # the O(Δ) block: composed ≈ appended, never ≈ cold
        "appends": appends,
        "ticks": ticks,
        "appends_per_tick": round(appends / max(ticks, 1), 2),
        "events_appended": appended_events,
        "events_replayed": replayed,
        "events_per_append": round(replayed / max(appends, 1), 2),
        "cold_events_equiv": cold_events,
        "suffix_frac": round(replayed / max(cold_events, 1), 4),
        "total_events": total_events,
        "cold_cohort_rebuild_ms": round(cold_cohort_ms, 3),
        "drain_flush_failed": drained["flush_failed"],
    }


def _bench_serve_overload(workflows: int, qps: float, lanes: int = 8,
                          capacity_frac: float = 0.5, domains: int = 3,
                          min_events: int = 20, max_events: int = 60,
                          delta_batches: int = 3,
                          tick_interval_ms: float = 5.0,
                          staleness_bound_ms: float = 500.0):
    """Graceful degradation under sustained overload (ISSUE 15).

    Offers an open-loop Poisson stream at ``qps`` against a limiter
    admitting only ``capacity_frac`` of it — sustained 1/capacity_frac×
    overload (the default is 2×). Workloads spread over ``domains``
    weighted domains through the fair-admission engine; rejected
    arrivals re-offer through a success-refilled RetryBudget; a
    background TickPump bounds resident staleness. The record reports
    the degradation ladder's observables: ``shed_frac`` (> 0 at 2× —
    excess load is shed, not queued into the p99), per-domain p99 +
    progress counters (no starvation), ``staleness_p99_ms`` vs the
    bound, and goodput vs offered."""
    import random as _random

    from cadence_tpu.ops import schema as S
    from cadence_tpu.serving import (
        AdmissionPolicy,
        ArrivalProcess,
        OpenLoopHarness,
        ResidentEngine,
        ServeWorkload,
        TickPump,
    )
    from cadence_tpu.testing import workloads as W
    from cadence_tpu.utils.metrics import Scope
    from cadence_tpu.utils.quotas import (
        MultiStageRateLimiter,
        RetryBudget,
    )

    caps = S.Capacities(
        max_events=512, max_activities=2, max_timers=2,
        max_children=2, max_request_cancels=2, max_signals_ext=4,
        max_version_items=2)
    dom_names = [f"dom-{d}" for d in range(domains)]

    def build(tag):
        rng = _random.Random(52)
        loads = []
        for i in range(workflows):
            batches = W.signal_history(
                rng, min_events=min_events, max_events=max_events)
            cut = max(1, int(len(batches) * 0.4))
            deltas = [
                batches[k : k + delta_batches]
                for k in range(cut, len(batches), delta_batches)
            ]
            loads.append(ServeWorkload(
                domain_id=dom_names[i % domains],
                workflow_id=f"ovl-{tag}-wf-{i}",
                run_id=f"ovl-{tag}-run-{i}", branch_token=b"",
                prefix=batches[:cut], deltas=deltas,
            ))
        return loads

    def drive(tag, scope):
        loads = build(tag)
        engine = ResidentEngine(
            lanes=lanes, caps=caps, metrics=scope, idle_ticks=2,
            admission=AdmissionPolicy(
                domain_weights={
                    d: float(2 ** (domains - i))
                    for i, d in enumerate(dom_names)
                },
                quota_rps=qps, aging_boost=1.0,
            ),
        )
        capacity = qps * capacity_frac
        harness = OpenLoopHarness(
            engine, loads,
            ArrivalProcess(qps=qps, seed=11),
            metrics=scope,
            limiter=MultiStageRateLimiter(
                global_rps=capacity,
                domain_rps=lambda d: capacity,
                global_burst=max(4, int(capacity / 8)),
            ),
            retry_budget=RetryBudget(ratio=0.2, cap=16.0, initial=8.0),
        )
        pump = TickPump(
            engine, tick_interval_ms / 1e3, metrics=scope
        ).start()
        try:
            run = harness.run()
        finally:
            pump.stop()
        return run, engine

    from cadence_tpu.utils.metrics import NOOP as _NOOP

    drive("warm", _NOOP)[1].drain()  # jit warm round, own registry
    scope = Scope()
    reg = scope.registry
    run, engine = drive("run", scope)
    drained = engine.drain()

    per_domain = {}
    for d in dom_names:
        stats = reg.timer_stats(
            "serve_decision",
            tags={"layer": "serving_harness", "domain": d},
        )
        prog = run["domains"].get(d, {})
        per_domain[d] = {
            "completed": prog.get("completed", 0),
            "shed": prog.get("shed", 0),
            "retries": prog.get("retries", 0),
            "p99_ms": round(stats.p99 * 1e3, 3),
        }
    stats = reg.timer_stats("serve_decision")
    staleness = reg.timer_stats("serving_staleness_ms")
    starvation = reg.timer_stats("serving_admit_starvation_age_ms")
    wall = max(run["wall_s"], 1e-9)
    return {
        "workflows": workflows,
        "lanes": lanes,
        "domains": domains,
        "qps_offered_target": round(qps, 1),
        "capacity_frac": capacity_frac,
        "requests": run["requests"],
        "offered": run["offered"],
        "retries": run["retries"],
        "completed": run["completed"],
        "shed": run["shed"],
        "shed_frac": round(run["shed"] / max(run["requests"], 1), 4),
        "offered_amplification": round(
            run["offered"] / max(run["requests"], 1), 3),
        "goodput_qps": round(run["completed"] / wall, 1),
        "offered_qps": round(run["offered"] / wall, 1),
        "latency_p50_ms": round(stats.p50 * 1e3, 3),
        "latency_p99_ms": round(stats.p99 * 1e3, 3),
        "per_domain": per_domain,
        "staleness_p99_ms": round(staleness.p99, 3),
        "staleness_bound_ms": staleness_bound_ms,
        "staleness_in_bound": bool(
            staleness.p99 <= staleness_bound_ms
        ),
        "starvation_age_max_ms": round(starvation.max_s, 3),
        "retry_budget_exhausted": reg.counter_value(
            "retry_budget_exhausted"
        ),
        "drain_flush_failed": drained["flush_failed"],
    }


def _bench_capacity_diurnal(workflows_per_chunk: int = 8,
                            qps_low: float = 60.0,
                            qps_high: float = 600.0,
                            chunks_low: int = 3, chunks_high: int = 4,
                            chunks_trough: int = 4, lanes: int = 16,
                            min_events: int = 12, max_events: int = 24,
                            initial_rps: float = 150.0):
    """Capacity autopilot closed loop under a diurnal curve (ISSUE 16).

    Offers a low -> high -> low open-loop stream against a live
    limiter the ``CapacityController`` retunes between chunks — the
    same sense (windowed serve_decision/serve_shed readings), decide
    (EWMA'd offered-demand + hysteresis gate + guardrail), actuate
    (``set_global_rate`` hook) loop the bootstrap wires. The record
    pins the autopilot story: the admission setpoint tracks the curve
    BOTH directions with zero operator calls and zero guardrail
    freezes, while per-phase p99/shed stay explicit fields."""
    import random as _random

    from cadence_tpu.config.static import AutopilotConfig
    from cadence_tpu.ops import schema as S
    from cadence_tpu.runtime.autopilot import (
        CapacityController,
        KEY_HISTORY_RPS,
    )
    from cadence_tpu.serving import (
        ArrivalProcess,
        OpenLoopHarness,
        ResidentEngine,
        ServeWorkload,
    )
    from cadence_tpu.testing import workloads as W
    from cadence_tpu.utils.metrics import NOOP as _NOOP, Scope, Window
    from cadence_tpu.utils.quotas import (
        MultiStageRateLimiter,
        RetryBudget,
    )

    caps = S.Capacities(
        max_events=512, max_activities=2, max_timers=2,
        max_children=2, max_request_cancels=2, max_signals_ext=4,
        max_version_items=2)

    def make_chunk(rng, serial, tag):
        loads = []
        for _ in range(workflows_per_chunk):
            serial[0] += 1
            batches = W.signal_history(
                rng, min_events=min_events, max_events=max_events)
            cut = max(1, int(len(batches) * 0.4))
            loads.append(ServeWorkload(
                domain_id=f"dom-{serial[0] % 2}",
                workflow_id=f"diurnal-{tag}-wf-{serial[0]}",
                run_id=f"diurnal-{tag}-run-{serial[0]}",
                branch_token=b"",
                prefix=batches[:cut],
                deltas=[batches[k:k + 2]
                        for k in range(cut, len(batches), 2)],
            ))
        return loads

    # jit warm round on its own engine/registry (serve_overload idiom)
    warm_engine = ResidentEngine(lanes=lanes, caps=caps, metrics=_NOOP,
                                 idle_ticks=2)
    OpenLoopHarness(
        warm_engine, make_chunk(_random.Random(41), [0], "warm"),
        ArrivalProcess(qps=qps_low, seed=5), metrics=_NOOP,
    ).run()
    warm_engine.drain()

    scope = Scope()
    reg = scope.registry
    engine = ResidentEngine(lanes=lanes, caps=caps, metrics=scope,
                            idle_ticks=2)
    limiter = MultiStageRateLimiter(
        global_rps=initial_rps, domain_rps=lambda d: 1e9)
    ap = CapacityController(
        AutopilotConfig(
            enabled=True, target_p99_ms=60_000.0, ewma_alpha=0.5,
            min_dwell=1, cooldown_epochs=0, max_step_frac=0.5,
            headroom_frac=0.5, min_rps=5.0),
        registry=reg,
        rate_hooks={KEY_HISTORY_RPS: limiter.set_global_rate},
        initial_rates={KEY_HISTORY_RPS: limiter.global_rps},
        metrics=scope,
    )
    rng = _random.Random(97)
    serial = [0]
    phase_window = Window(reg)

    def run_phase(name, qps, chunks):
        for _ in range(chunks):
            OpenLoopHarness(
                engine, make_chunk(rng, serial, name),
                ArrivalProcess(qps=qps, seed=serial[0]),
                metrics=scope, limiter=limiter,
                retry_budget=RetryBudget(ratio=0.2, cap=16.0,
                                         initial=8.0),
            ).run()
            ap.run_epoch_once()
        r = phase_window.advance()
        st = r.timer_stats("serve_decision")
        shed = r.counter("serve_shed")
        return {
            "chunks": chunks,
            "offered_qps_target": round(qps, 1),
            "admitted": st.count,
            "shed": shed,
            "shed_frac": round(shed / max(shed + st.count, 1), 4),
            "p99_ms": round(st.p99 * 1e3, 3),
            "rate_rps": round(
                ap.status()["rates"][KEY_HISTORY_RPS], 2),
            "demand_rps": round(
                r.gauge("autopilot_demand_rps"), 2),
        }

    try:
        low = run_phase("low", qps_low, chunks_low)
        high = run_phase("high", qps_high, chunks_high)
        trough = run_phase("trough", qps_low, chunks_trough)
    finally:
        drained = engine.drain()

    status = ap.status()
    st = reg.timer_stats("serve_decision")
    total_shed = reg.counter_value("serve_shed")
    ap_tags = {"layer": "autopilot"}
    operator_calls = (
        reg.counter_value("autopilot_pauses", tags=ap_tags)
        + reg.counter_value("autopilot_resumes", tags=ap_tags)
    )
    return {
        "workflows_per_chunk": workflows_per_chunk,
        "lanes": lanes,
        "qps_low": round(qps_low, 1),
        "qps_high": round(qps_high, 1),
        "initial_rps": round(initial_rps, 1),
        "phases": {"low": low, "high": high, "trough": trough},
        "rate_low_rps": low["rate_rps"],
        "rate_high_rps": high["rate_rps"],
        "rate_final_rps": trough["rate_rps"],
        "rate_tracks_load": bool(
            high["rate_rps"] > low["rate_rps"] * 1.2
            and trough["rate_rps"] < high["rate_rps"]
        ),
        "epochs": status["epochs_run"],
        "retunes": reg.counter_value(
            "autopilot_rate_retunes", tags=ap_tags),
        "guardrail_freezes": status["guardrail_freezes"],
        "gate_switches": status["gate_switches"],
        "overloaded_final": status["overloaded"],
        "operator_calls": operator_calls,
        "p99_overall_ms": round(st.p99 * 1e3, 3),
        "shed_frac_overall": round(
            total_shed / max(total_shed + st.count, 1), 4),
        "drain_flush_failed": drained["flush_failed"],
    }


def _bench_telemetry_overhead(calls: int = 30000, rounds: int = 5):
    """Unsampled telemetry cost on the instrumented serving path.

    The telemetry plane's contract is that DISABLED tracing is nearly
    free: the instrument_methods wrapper's tracing hook is one
    thread-local read returning a shared no-op. This config measures an
    echo-shaped handler op (the serving hot path's wrapper stack, no
    kernel noise) three ways — a metrics-only control wrapper (the
    pre-telemetry shape), the real tracing-aware wrapper with NO active
    trace (unsampled), and the same wrapper inside a sampled trace —
    and reports the unsampled overhead fraction the smoke contract pins
    at ≤3% (tests/test_bench_smoke.py). Rates are best-of-``rounds`` so
    host-load noise shrinks the estimate, never inflates it.
    """
    from cadence_tpu.rpc import codec
    from cadence_tpu.utils import metrics_defs
    from cadence_tpu.utils.metrics import Scope
    from cadence_tpu.utils.tracing import TRACER

    # an echo request's cheapest honest unit of work: the rpc codec
    # roundtrip of a start-shaped payload (tens of µs — far BELOW the
    # ms-scale cost of a real Onebox echo decision, so the measured
    # overhead fraction is an upper bound on the serving-path one)
    payload = {
        "domain": "bench", "workflow_id": "echo-wf-0000",
        "workflow_type": "echo", "task_list": "bench-tl",
        "input": "x" * 256, "request_id": "req-0000",
        "timeout_seconds": 60, "identity": "bench-worker",
    }

    class _Echo:
        def echo(self, i):
            return codec.loads(codec.dumps(([payload], {"seq": i})))

    instrumented = _Echo()
    metrics_defs.instrument_methods(
        instrumented, Scope().tagged(service="bench"), ("echo",)
    )

    control = _Echo()
    ctrl_scope = Scope().tagged(service="bench", operation="echo")
    ctrl_fn = control.echo

    def ctrl_wrapped(*args, **kwargs):
        ctrl_scope.inc(metrics_defs.REQUESTS)
        t0 = time.perf_counter()
        try:
            return ctrl_fn(*args, **kwargs)
        finally:
            ctrl_scope.record(
                metrics_defs.LATENCY, time.perf_counter() - t0
            )

    control.echo = ctrl_wrapped

    import gc as _gc

    def _round(target):
        op = target.echo
        t0 = time.perf_counter()
        for i in range(calls):
            op(i)
        return time.perf_counter() - t0

    # paired interleaved rounds: each round times control then
    # instrumented back to back, so slow host-load drift cancels in the
    # per-round ratio; the reported overhead is the MINIMUM paired
    # ratio — timing noise on this codec-bound loop is strictly
    # additive, so every observed ratio is an upper bound on the true
    # wrapper cost and the tightest one is the honest estimate. GC is
    # paused through the rounds (allocation-heavy codec bodies
    # otherwise donate multi-percent variance to whichever arm the
    # collector fires in).
    _round(control), _round(instrumented)  # warm both paths
    ratios = []
    best = {"ctrl": None, "inst": None}
    _gc.disable()
    try:
        for _ in range(rounds):
            dt_c = _round(control)
            dt_i = _round(instrumented)
            ratios.append(dt_i / dt_c)
            if best["ctrl"] is None or dt_c < best["ctrl"]:
                best["ctrl"] = dt_c
            if best["inst"] is None or dt_i < best["inst"]:
                best["inst"] = dt_i
        with TRACER.trace("bench_telemetry_overhead", sampled=True):
            sampled = calls / min(
                _round(instrumented) for _ in range(rounds)
            )
    finally:
        _gc.enable()
    untraced = calls / best["ctrl"]
    unsampled = calls / best["inst"]
    TRACER.clear()  # the bench spans must not linger in the recorder
    overhead = min(ratios) - 1.0
    return {
        "calls_per_round": calls,
        "rounds": rounds,
        "untraced_calls_per_sec": round(untraced, 1),
        "unsampled_calls_per_sec": round(unsampled, 1),
        "sampled_calls_per_sec": round(sampled, 1),
        # the guarded number: unsampled tracing vs the metrics-only
        # wrapper, min over the paired rounds (negative = measurement
        # noise in telemetry's favor)
        "overhead_unsampled_frac": round(overhead, 4),
        "overhead_unsampled_frac_median": round(
            sorted(ratios)[len(ratios) // 2] - 1.0, 4),
        "overhead_sampled_frac": round(untraced / sampled - 1.0, 4),
    }


def _bench_queue_drain(tasks_per_queue=2000, n_wf=48, parallelism=4,
                       batch_size=128, close_every=500, stall_us=150):
    """Queue-drain throughput: sequential pump vs the conflict-keyed
    wave executor (runtime/queues/parallel.py) over an identical mixed
    transfer/timer storm.

    Three queue pipelines (two transfer shards + one timer) carry
    ``tasks_per_queue`` rows each, round-robin over ``n_wf`` workflows
    with a sprinkle of CloseExecution (the untargeted cross-workflow
    fan-out that serializes its cycle). Both arms run the identical
    handler — a commutative per-(workflow, task-type) accumulator — so
    the final state must match byte-for-byte. The sequential arm is
    the production one-task-at-a-time drain (``QueueProcessorBase``
    own pump, one worker: per-task ack lock + per-task pool submit);
    the parallel arm registers the same pipelines on one shared
    ``ParallelQueueExecutor`` gated on the regenerated conflict-matrix
    artifact (``ensure_conflict_matrix``).

    Each task carries a ``stall_us`` GIL-releasing stall modeling the
    persistence/matching round-trip a real transfer or timer task
    spends most of its wall-clock in — the latency the wave executor
    exists to overlap: the ordered baseline pays it serially, while
    provably-commuting conflict groups overlap it across the worker
    pool (plus batched ack-lock and per-group instead of per-task
    submit amortization). The baseline is ``worker_count=1`` because
    that is the configuration with the SAME ordering guarantee the
    wave schedule preserves; a wider naive pool overlaps arbitrary
    tasks with no commutativity proof. The smoke contract
    (tests/test_bench_smoke.py) pins the record shape, state equality,
    and the non-degraded matrix gate; real runs carry the >=2x
    speedup acceptance bar.
    """
    import threading as _threading
    from types import SimpleNamespace

    from cadence_tpu.core.enums import TimerTaskType, TransferTaskType
    from cadence_tpu.runtime.queues.ack import QueueAckManager
    from cadence_tpu.runtime.queues.base import QueueProcessorBase
    from cadence_tpu.runtime.queues.parallel import (
        ParallelQueueExecutor,
        ensure_conflict_matrix,
    )

    queues = ("transfer-0", "transfer-1", "timer-0")

    # closes live at the storm's tail — a workflow's CloseExecution is
    # the last task of its lifecycle, not a uniform sprinkle. The
    # untargeted fan-out serializes its whole cycle, so tail placement
    # also keeps the serialized window where a real drain has it: at
    # the end, once the commuting bulk has already overlapped
    n_close = (tasks_per_queue // close_every) if close_every else 0

    def _mk_tasks(queue):
        rows = []
        for i in range(tasks_per_queue):
            if queue.startswith("timer"):
                tt = (TimerTaskType.UserTimer if i % 3
                      else TimerTaskType.ActivityTimeout)
            elif i >= tasks_per_queue - n_close:
                tt = TransferTaskType.CloseExecution
            else:
                tt = (TransferTaskType.DecisionTask if i % 2
                      else TransferTaskType.ActivityTask)
            rows.append(SimpleNamespace(
                task_id=i + 1, task_type=tt, domain_id="bench",
                workflow_id=f"wf-{i % n_wf}", run_id=f"run-{i % n_wf}",
                target_workflow_id="", target_domain_id="",
            ))
        return rows

    total = len(queues) * tasks_per_queue

    def _run_arm(executor):
        state = {}
        lock = _threading.Lock()
        done = _threading.Event()
        counter = [0]

        def process(task):
            # the persistence/matching round-trip stand-in (GIL
            # released, like the real blocking call)
            if stall_us:
                time.sleep(stall_us / 1e6)
            # commutative per-(workflow, type) accumulator: commuting
            # reorder cannot change it, a lost/duplicated task must.
            # The last task trips the event — drain completion is
            # detected on the worker side, not through a polling loop
            # whose sleep quantum would swamp the measurement
            key = f"{task.workflow_id}:{int(task.task_type)}"
            with lock:
                state[key] = state.get(key, 0) + task.task_id
                counter[0] += 1
                if counter[0] == total:
                    done.set()

        procs = []
        for q in queues:
            rows = _mk_tasks(q)

            def read(level, limit, rows=rows):
                return [t for t in rows if t.task_id > level][:limit]

            procs.append(QueueProcessorBase(
                name=q, ack=QueueAckManager(0), read_batch=read,
                process_task=process, complete_task=lambda t: None,
                task_key=lambda t: t.task_id,
                worker_count=1,  # the one-task-at-a-time baseline
                batch_size=batch_size, poll_interval_s=0.005,
                executor=executor,
            ))
        t0 = time.perf_counter()
        for p in procs:
            p.start()
        if executor is not None:
            executor.start()
            executor.notify()
        else:
            for p in procs:
                p.notify()
        drained = done.wait(timeout=120.0)
        dt = time.perf_counter() - t0
        # let the final acks land and the watermark sweep before teardown
        sweep_deadline = time.monotonic() + 10.0
        while time.monotonic() < sweep_deadline:
            if all(p.ack.update_ack_level() >= tasks_per_queue
                   for p in procs):
                break
            time.sleep(0.002)
        for p in procs:
            p.stop()
        if executor is not None:
            executor.stop()
        rate = total / dt if dt > 0 else 0.0
        return state, rate, drained

    seq_state, seq_rate, seq_drained = _run_arm(None)
    ex = ParallelQueueExecutor(
        parallelism=parallelism, batch_size=batch_size,
        poll_interval_s=0.005,
        matrix_path=ensure_conflict_matrix(
            "build/queue_conflict_matrix.json"),
    )
    par_state, par_rate, par_drained = _run_arm(ex)
    return {
        "tasks": len(queues) * tasks_per_queue,
        "queues": len(queues),
        "n_workflows": n_wf,
        "parallelism": parallelism,
        "seq_tasks_per_sec": round(seq_rate, 1),
        "par_tasks_per_sec": round(par_rate, 1),
        "speedup": round(par_rate / seq_rate, 2) if seq_rate else 0.0,
        # mean concurrent conflict groups per shared cycle (the
        # parqueue_wave_width metric) and the fraction of tasks folded
        # into an already-open group (parqueue_conflict_frac)
        "wave_width_mean": round(ex.waves / max(1, ex.cycles), 2),
        "conflict_frac": round(1.0 - ex.waves / max(1, ex.tasks), 4),
        "cycles": ex.cycles,
        "stale_skipped": ex.stale_skipped,
        "degraded": ex.degraded,
        "drained": bool(seq_drained and par_drained),
        "state_identical": seq_state == par_state,
    }


def _checksum(state):
    acc = jnp.int32(0)
    for leaf in jax.tree_util.tree_leaves(state):
        acc = acc + jnp.sum(leaf, dtype=jnp.int32)
    return acc


def _time_chained(fn, state0, iters):
    """fn: state -> (state, aux). Forced-materialization amortized s/call.

    Chains the state through ``iters`` calls and fetches a checksum that
    data-depends on the last call's full output (state + aux)."""
    cs = jax.jit(lambda out: _checksum(out))
    out = fn(state0)                      # compile + warm
    np.asarray(cs(out))
    t0 = time.perf_counter()
    st = state0
    for _ in range(iters):
        out = fn(st)
        st = out[0]
    v = int(np.asarray(cs(out)))
    return (time.perf_counter() - t0) / iters, v


def measure_copy_bw_gbps(nbytes: int = 1 << 28) -> float:
    """Measured r+w HBM bandwidth of a jitted elementwise copy."""
    x = jax.jit(lambda k: jax.random.randint(
        k, (nbytes // 4,), 0, 100, jnp.int32))(jax.random.PRNGKey(0))
    f = jax.jit(lambda x: x + 1)
    y = f(x)
    np.asarray(jnp.sum(y[:1]))
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        y = f(y)
    np.asarray(jnp.sum(y[:1]))
    dt = (time.perf_counter() - t0) / iters
    return 2 * nbytes / dt / 1e9


def _bench_config(config: str, caps, batch: int, iters: int,
                  baseline_histories: int, bt: int, tb: int,
                  use_pallas: bool, chain: int = 1,
                  depth_curve: bool = False):
    """Returns a per-config result dict.

    ``chain`` > 1 additionally times ``chain`` kernel executions inside
    ONE jit dispatch (lax.scan over the replay+refresh step) after the
    single-dispatch run has proven checksum parity. Through the axon
    tunnel a dispatch costs ~20ms of rig RTT that production TPU hosts
    don't pay; the chained number amortizes it to 1/chain and is the
    honest steady-state device throughput. Both numbers are reported.
    """
    from cadence_tpu import native
    from cadence_tpu.native import presence_masks
    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.pack import pack_histories
    from cadence_tpu.ops.refresh import refresh_tasks_device
    from cadence_tpu.ops.replay import replay_scan, type_signature
    from cadence_tpu.ops.replay_pallas import replay_scan_pallas_teb

    n_unique = min(32, batch)
    packed = pack_histories(_build_histories(config, n_unique, caps),
                            caps=caps)
    events, lengths = _tile(packed, batch)
    # static event-type specialization, exactly as the serving
    # dispatcher applies it (DeviceDispatcher._type_set)
    types = type_signature(
        int(t) for t in np.unique(events[:, :, S.EV_TYPE]) if t >= 0)
    mean_depth = float(lengths.mean())
    T = events.shape[1]
    state0 = jax.device_put(
        jax.tree_util.tree_map(jnp.asarray, S.empty_state(batch, caps))
    )
    state_bytes = sum(x.size * 4 for x in jax.tree_util.tree_leaves(state0))
    ev_bytes_step = batch * S.EV_N * 4

    results = {}

    # ---- XLA scan kernel
    ev_tm = jnp.asarray(np.ascontiguousarray(np.transpose(events, (1, 0, 2))))

    def step_xla(state):
        final = replay_scan(state, ev_tm, types=types)
        return final, refresh_tasks_device(final)

    dt, cs_xla = _time_chained(jax.jit(step_xla), state0, iters)
    results["xla"] = {
        "histories_per_sec": round(batch / dt, 2),
        "batch_rebuild_ms": round(dt * 1000, 3),
        "us_per_step": round(dt / T * 1e6, 3),
        # state read+write + event row read, per scan step
        "streams_gbps": round(
            (2 * state_bytes + ev_bytes_step) / (dt / T) / 1e9, 1),
    }

    # ---- associative (parallel-in-time) kernel: segmented composition
    # of affine transition updates (ops/assoc.py) — O(log T) depth
    # instead of the scan's O(T). Same batch, same types, same
    # replay+refresh step; parity is asserted via the chained checksum
    # before any number is recorded.
    from cadence_tpu.ops.assoc import _assoc_core, events_fm_of

    evf = jnp.asarray(events_fm_of(events))

    def step_assoc(state):
        final = _assoc_core(evf, state, types=types)
        return final, refresh_tasks_device(final)

    try:
        dt_a, cs_a = _time_chained(jax.jit(step_assoc), state0, iters)
        if cs_a != cs_xla:
            results["assoc"] = {"error": "checksum mismatch vs xla"}
        else:
            results["assoc"] = {
                "histories_per_sec": round(batch / dt_a, 2),
                "batch_rebuild_ms": round(dt_a * 1000, 3),
                "us_per_step": round(dt_a / T * 1e6, 3),
                # the depth-insensitivity headline: wall time of the
                # assoc kernel over the sequential scan's on this batch
                "vs_scan": round(dt / dt_a, 2),
            }
    except Exception as exc:
        results["assoc"] = {
            "error": f"{type(exc).__name__}: {str(exc)[:160]}"}

    # ---- us_per_step depth-scaling curve (assoc vs scan): replay event
    # PREFIXES of geometrically growing depth. The scan's us_per_step is
    # ~flat (cost O(T)); the assoc kernel's FALLS with depth (cost
    # O(log T) depth, so wall time is sublinear in T) — the curve is the
    # BENCH record of that crossover.
    if depth_curve and "error" not in results["assoc"]:
        curve = []
        # two points bound the compile cost (each new scan length is a
        # fresh — minutes-scale cold — sequential-scan compile)
        for d in sorted({max(T // 4, 8), T}):
            ev_d = events[:, :d]
            ev_tm_d = jnp.asarray(
                np.ascontiguousarray(np.transpose(ev_d, (1, 0, 2))))
            evf_d = jnp.asarray(events_fm_of(ev_d))
            dt_s, _ = _time_chained(
                jax.jit(lambda s: (replay_scan(s, ev_tm_d, types=types),
                                   None)),
                state0, max(2, iters // 2))
            dt_p, _ = _time_chained(
                jax.jit(lambda s: (_assoc_core(evf_d, s, types=types),
                                   None)),
                state0, max(2, iters // 2))
            curve.append({
                "depth": d,
                "scan_us_per_step": round(dt_s / d * 1e6, 3),
                "assoc_us_per_step": round(dt_p / d * 1e6, 3),
                "vs_scan": round(dt_s / dt_p, 2),
            })
        results["assoc"]["depth_curve"] = curve
    del ev_tm

    # ---- Pallas kernel (field-major events + host presence masks)
    if use_pallas:
        from cadence_tpu.ops.replay_pallas import narrow_events_teb

        ev_teb_np = np.ascontiguousarray(np.transpose(events, (1, 2, 0)))
        ev_teb = jnp.asarray(ev_teb_np)
        valid = events[:, :, S.EV_TYPE] >= 0
        pres = None
        if batch % bt == 0:
            pres = jnp.asarray(presence_masks(
                events[valid], valid.sum(axis=1).astype(np.int64), T, bt))

        def step_pallas(state):
            final = replay_scan_pallas_teb(
                state, ev_teb, caps, tb=tb, interpret=False, bt=bt,
                presence=pres)
            return final, refresh_tasks_device(final)

        def _chained(kernel_kwargs):
            """One jit dispatch running ``chain`` replay+refresh steps
            (lax.scan) — amortizes the per-dispatch rig RTT. Returns
            amortized seconds per step."""
            from jax import lax

            def stepped(state):
                def body(c, _):
                    final = replay_scan_pallas_teb(
                        c, caps=caps, tb=tb, interpret=False, bt=bt,
                        presence=pres, **kernel_kwargs)
                    return final, refresh_tasks_device(final)

                return lax.scan(body, state, None, length=chain)

            dt_c, _ = _time_chained(
                jax.jit(stepped), state0, max(2, iters // 2))
            return dt_c / chain

        try:
            dt_p, cs_p = _time_chained(jax.jit(step_pallas), state0, iters)
            if cs_p != cs_xla:
                results["pallas"] = {"error": "checksum mismatch vs xla"}
            else:
                results["pallas"] = {
                    "histories_per_sec": round(batch / dt_p, 2),
                    "batch_rebuild_ms": round(dt_p * 1000, 3),
                    "us_per_step": round(dt_p / T * 1e6, 3),
                    "streams_gbps": round(ev_bytes_step / (dt_p / T) / 1e9, 1),
                }
                if chain > 1:
                    per_exec = _chained({"events_teb": ev_teb})
                    results["pallas"].update({
                        "chain": chain,
                        "histories_per_sec_chained": round(
                            batch / per_exec, 2),
                        "batch_rebuild_ms_chained": round(
                            per_exec * 1000, 3),
                        "dispatch_overhead_ms": round(
                            (dt_p - per_exec) * 1000, 3),
                    })
        except Exception as exc:  # compile/runtime failure is a reportable
            results["pallas"] = {
                "error": f"{type(exc).__name__}: {str(exc)[:160]}"}

        # ---- int16 narrow stream: the kernel is event-stream-bound,
        # so ~halving its bytes is the per-tile lever (r5); parity is
        # asserted against the XLA checksum before any number is kept
        pallas_ok = "histories_per_sec" in results.get("pallas", {})
        narrowed = narrow_events_teb(ev_teb_np) if pallas_ok else None
        if narrowed is not None:
            ev16_np, nbase, nwide = narrowed
            ev16 = jnp.asarray(ev16_np)
            n16 = {"events_teb": ev16, "base": nbase, "wide_cols": nwide}

            def step_pallas16(state):
                final = replay_scan_pallas_teb(
                    state, caps=caps, tb=tb, interpret=False, bt=bt,
                    presence=pres, **n16)
                return final, refresh_tasks_device(final)

            try:
                dt_n, cs_n = _time_chained(
                    jax.jit(step_pallas16), state0, iters)
                if cs_n != cs_xla:
                    results["pallas16"] = {"error": "checksum mismatch"}
                else:
                    results["pallas16"] = {
                        "histories_per_sec": round(batch / dt_n, 2),
                        "batch_rebuild_ms": round(dt_n * 1000, 3),
                        "us_per_step": round(dt_n / T * 1e6, 3),
                        "wide_cols": list(nwide),
                        "stream_bytes_frac": round(
                            ev16_np.shape[1] * 2 / (S.EV_N * 4), 3),
                    }
                    if chain > 1:
                        per_exec16 = _chained(n16)
                        results["pallas16"].update({
                            "chain": chain,
                            "histories_per_sec_chained": round(
                                batch / per_exec16, 2),
                            "batch_rebuild_ms_chained": round(
                                per_exec16 * 1000, 3),
                        })
            except Exception as exc:
                results["pallas16"] = {
                    "error": f"{type(exc).__name__}: {str(exc)[:160]}"}

    # ---- compiled-host baseline: C++ sequential replay of the same tensors
    class _Sub:
        pass

    sub = _Sub()
    nb = min(baseline_histories, batch)
    sub.events = events[:nb]
    sub.lengths = lengths[:nb]
    sub.caps = caps
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < 0.5:
        native.replay_sequential(sub)
        reps += 1
    cpp_s = (time.perf_counter() - t0) / reps
    cpp_rate = nb / cpp_s

    def _rate(k):
        # SELECTION compares per-dispatch rates only (every kernel has
        # one; mixing chained and unchained regimes would let a
        # dispatch-amortized pallas beat an unchained-but-faster xla)
        r = results.get(k, {})
        return r.get("histories_per_sec", -1.0)

    best_key = max(("xla", "assoc", "pallas", "pallas16"), key=_rate)
    best = results[best_key]
    # steady-state (dispatch-amortized) rate is the headline when the
    # chained run exists; the per-dispatch rate stays in "kernels".
    # batch_rebuild_ms is derived from the SAME regime as the headline
    # rate — mixing the chained rate with the unchained latency made
    # the record self-contradictory (recomputing histories/s from the
    # *_ms fields disagreed with "value"; ADVICE r5)
    headline_rate = best.get(
        "histories_per_sec_chained", best["histories_per_sec"]
    )
    out = {
        "histories_per_sec": headline_rate,
        "kernel": best_key,
        "baseline_cpp_per_sec": round(cpp_rate, 2),
        "vs_baseline": round(headline_rate / cpp_rate, 2),
        "mean_depth": round(mean_depth, 1),
        "batch_rebuild_ms": round(batch / headline_rate * 1000, 3),
        "batch_rebuild_ms_unchained": best["batch_rebuild_ms"],
        "batch": batch,
        # padded steps ÷ real events: the per-lane padding waste the
        # lane-packed configs eliminate (one history per lane here)
        "padding_frac": round(
            float(batch * T - lengths.sum()) / max(int(lengths.sum()), 1),
            4),
        "lanes_per_history": 1.0,
        "kernels": results,
    }
    # the assoc-vs-scan trajectory BENCH_r06+ tracks, surfaced at
    # config level so trend tooling doesn't dig through "kernels"
    if "vs_scan" in results.get("assoc", {}):
        out["vs_scan"] = results["assoc"]["vs_scan"]
    return out


def main() -> None:
    from cadence_tpu import native
    from cadence_tpu.ops import schema as S

    if native._load() is None:
        _emit(_fail_record("native baseline unavailable (no g++)"))
        return

    wall_s = float(os.environ.get("BENCH_WALL_S", "2100"))
    _watchdog(wall_s)

    # explicit backend record: how the platform was chosen is a field of
    # the JSON (BENCH_r05's tail-note form was unparseable by trend
    # tooling), and a healthy probe result is cached across runs
    backend_note = None
    if "--cpu" in sys.argv:
        backend = {"platform": "cpu", "probe": "forced-cpu"}
    elif os.environ.get("BENCH_SIM_PROBE_FAIL") == "1":
        # test hook (tests/test_bench_smoke.py): behave exactly as if
        # the accelerator probe died — the record must degrade to the
        # flagged CPU fallback with backend_note set and still exit 0
        jax.config.update("jax_platforms", "cpu")
        backend = {"platform": "cpu", "probe": "failed-or-timeout",
                   "fallback": True}
        backend_note = (
            "accelerator probe failed-or-timeout (simulated); "
            "degraded to CPU fallback")
    elif SMOKE:
        jax.config.update("jax_platforms", "cpu")
        backend = {"platform": "cpu", "probe": "smoke"}
    else:
        plat, probe = _probe_backend(
            float(os.environ.get("BENCH_PROBE_S", "120")),
            float(os.environ.get("BENCH_PROBE_TTL_S", "3600")))
        if plat is None:
            # tunnel dead/wedged: a flagged CPU run beats an empty record
            jax.config.update("jax_platforms", "cpu")
            backend = {"platform": "cpu", "probe": probe,
                       "fallback": True}
            backend_note = (
                f"accelerator probe {probe}; degraded to CPU fallback")
        else:
            backend = {"platform": plat, "probe": probe}

    # first in-process backend touch, guarded: the probe can succeed
    # and the in-process plugin init still throw mid-run (BENCH_r04
    # died rc=1 there) — any backend/plugin init failure degrades to
    # the CPU-fallback record with backend_note set, never a crash
    try:
        if (os.environ.get("BENCH_SIM_BACKEND_INIT_FAIL") == "1"
                and not backend.get("fallback")):
            raise RuntimeError("simulated backend plugin init failure")
        on_cpu = jax.default_backend() == "cpu"
    except Exception as init_exc:
        try:
            jax.config.update("jax_platforms", "cpu")
            on_cpu = jax.default_backend() == "cpu"
        except Exception as cpu_exc:  # even CPU won't init: fail record
            _emit(_fail_record(
                f"backend init failed ({type(init_exc).__name__}: "
                f"{str(init_exc)[:120]}) and CPU fallback failed "
                f"({type(cpu_exc).__name__})"))
            return
        backend = {"platform": "cpu",
                   "probe": backend.get("probe", "unknown"),
                   "fallback": True}
        backend_note = (
            f"backend init failed ({type(init_exc).__name__}: "
            f"{str(init_exc)[:160]}); degraded to CPU fallback")
    # the Pallas kernel needs the real chip; interpret mode is a test
    # vehicle, not a benchmark
    use_pallas = not on_cpu
    scale = 1 if on_cpu else 128
    iters = 3 if on_cpu else 5
    bt, tb = 8192, 16
    if SMOKE:
        scale, iters = 1, 1

    # per-config capacities: sized to the workload (slot tables directly
    # set HBM bytes/step for the XLA kernel and VMEM rows for Pallas)
    CONFIGS = {
        # echo rides the lane-packed path: ~23 whole 11-event histories
        # per 256-step lane instead of one 11-event history per 16-step
        # lane — the scan replays ~16/11x more real events per step and
        # the type-specialized step body skips the transition blocks an
        # echo storm never touches
        "echo": dict(
            caps=S.Capacities(max_events=16, max_activities=2, max_timers=2,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=2, max_version_items=2),
            batch=512 * scale, baseline=2048,
            # column-layout per-step cost grows sublinearly in lanes, so
            # the packed grid uses the widest batch that still fits the
            # bench wall (~47k whole histories per 256-step scan)
            packed=dict(lanes=min(2048 * scale, 8192), lane_len=256)),
        # 90% depth-16 / 10% depth-1k: the depth-bucketed dispatch
        # configuration — without bucketing+packing every lane pads to
        # the 1k stragglers (unpacked_histories_per_sec reports that)
        "mixed_depth": dict(
            caps=S.Capacities(max_events=1024, max_activities=4,
                              max_timers=2, max_children=2,
                              max_request_cancels=2, max_signals_ext=2,
                              max_version_items=2),
            batch=512 * scale, baseline=512,
            packed=dict(lanes=min(512 * scale, 4096), lane_len=1024)),
        "signal": dict(
            caps=S.Capacities(max_events=512, max_activities=2, max_timers=2,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=4, max_version_items=2),
            batch=512 * scale, baseline=512),
        "timer_storm": dict(
            caps=S.Capacities(max_events=512, max_activities=2, max_timers=16,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=2, max_version_items=2),
            batch=512 * scale, baseline=512),
        "retry_deep": dict(
            caps=S.Capacities(max_events=1024, max_activities=4, max_timers=2,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=2, max_version_items=2),
            batch=512 * scale, baseline=256),
        "ndc_storm": dict(
            caps=S.Capacities(max_events=1024),  # full default tables
            batch=256 * scale, baseline=256),
        # checkpointed incremental replay: rebuild the same retry_deep
        # cohort twice — the second pass resumes from prefix snapshots
        # and replays only the tail (cadence_tpu/checkpoint/). Host-loop
        # bound (full rebuild_many pipeline), so the cohort stays modest
        "rebuild_warm": dict(
            warm=dict(n=96 if on_cpu else 256, depth=1000, iters=2)),
        # elastic resharding under live traffic: shard split mid-run,
        # decision-latency probes through the fenced window
        # (runtime/resharding.py; README "Elastic resharding")
        "reshard_live": dict(reshard=dict(duration_s=16.0)),
        # geo-replication catch-up on a throttled WAN link: event-ship
        # vs snapshot-ship vs adaptive (runtime/replication/transport.py;
        # README "Adaptive geo-replication")
        "replication_lag": dict(lag=dict(
            workflows=12, signals_each=48, bytes_per_s=131072.0)),
        # domain failover drills: managed handover, forced region-loss
        # promotion with a conflict storm, failback — per-scenario
        # unavailability + replication-lag SLOs
        # (runtime/replication/failover.py; README "Domain failover")
        "failover_drill": dict(failover=dict(
            workflows=6, signals_each=24, bytes_per_s=131072.0)),
        # continuous-batching serving under open-loop load: resident
        # O(Δ) appends at sustained QPS, p50/p99 decision-latency SLOs
        # (cadence_tpu/serving/; README "Continuous-batching serving")
        "serve_continuous": dict(serve=dict(
            workflows=48, qps=300.0, lanes=64)),
        # graceful degradation under sustained 2x overload: fair
        # admission + retry budgets + the tick pump's staleness bound
        # (ISSUE 15; README "Overload control")
        "serve_overload": dict(overload=dict(
            workflows=24, qps=400.0, lanes=8, capacity_frac=0.5)),
        # closed-loop capacity autopilot under a diurnal load curve:
        # the admission setpoint must track offered load BOTH ways
        # with zero operator calls and zero guardrail freezes
        # (ISSUE 16; README "Capacity autopilot")
        "capacity_diurnal": dict(diurnal=dict(
            workflows_per_chunk=8, qps_low=60.0, qps_high=600.0,
            chunks_low=3, chunks_high=4, chunks_trough=4, lanes=16)),
        # unsampled telemetry cost on the instrumented serving path:
        # the ≤3% guard tests/test_bench_smoke.py pins (utils/tracing)
        "telemetry_overhead": dict(telemetry=dict(
            calls=20000, rounds=5)),
        # conflict-keyed wave executor vs the sequential pump over an
        # identical mixed transfer/timer storm (runtime/queues/
        # parallel.py; README "Parallel queue execution") — the >=2x
        # tasks/sec acceptance bar rides this record
        "queue_drain": dict(qdrain=dict(
            tasks_per_queue=2000, n_wf=48, parallelism=12,
            stall_us=250)),
    }

    if SMOKE:
        # harness-coverage shapes: tiny tensors, seconds on CPU — one
        # unpacked config plus one lane-packed/bucketed config so the
        # packer's padding_frac contract stays under tier-1 coverage
        smoke_caps = S.Capacities(
            max_events=64, max_activities=4, max_timers=2,
            max_children=2, max_request_cancels=2,
            max_signals_ext=2, max_version_items=2)
        CONFIGS = {
            "retry_deep": dict(caps=smoke_caps, batch=32, baseline=32),
            "mixed_depth": dict(
                caps=smoke_caps, batch=32, baseline=32,
                packed=dict(lanes=8, lane_len=64)),
            # lane-packed echo at smoke scale: pins the histogram
            # latency contract (Registry.timer_stats-backed p50/p99 in
            # the record) on the serving-shaped config
            "echo": dict(
                caps=smoke_caps, batch=32, baseline=32,
                packed=dict(lanes=8, lane_len=64)),
            # checkpoint-resume contract coverage (suffix_frac < 1.0,
            # checkpoint_hit_rate reported) at seconds-scale shapes
            "rebuild_warm": dict(warm=dict(n=24, depth=40, iters=1)),
            # reshard JSON contract at seconds-scale load
            "reshard_live": dict(
                reshard=dict(duration_s=2.0, probe_interval_s=0.02)),
            # adaptive-replication contract: tiny backlog, link slow
            # enough that the byte asymmetry (compressed snapshot <<
            # hydrated event backlog) dominates host-load noise
            "replication_lag": dict(lag=dict(
                workflows=3, signals_each=20, bytes_per_s=24576.0)),
            # failover-drill JSON contract at seconds-scale load
            "failover_drill": dict(failover=dict(
                workflows=2, signals_each=8, bytes_per_s=131072.0)),
            # open-loop serving SLO contract at seconds-scale load
            "serve_continuous": dict(serve=dict(
                workflows=6, qps=120.0, lanes=8,
                min_events=20, max_events=48)),
            # overload JSON contract: 2x offered load over a tiny
            # capacity bucket — shed_frac > 0, every domain progresses,
            # staleness stays bounded, all at seconds scale
            "serve_overload": dict(overload=dict(
                workflows=9, qps=150.0, lanes=4, capacity_frac=0.5,
                min_events=16, max_events=32)),
            # capacity-autopilot JSON contract at seconds scale: the
            # setpoint tracks low->high->low, zero guardrail freezes,
            # zero operator calls
            # (4 trough chunks: the demand EWMA needs the extra epoch
            # to decay visibly below the peak on a slow/contended CPU,
            # where compute bounds the offered rate and compresses the
            # low-vs-high dynamic range)
            "capacity_diurnal": dict(diurnal=dict(
                workflows_per_chunk=4, qps_low=30.0, qps_high=300.0,
                chunks_low=2, chunks_high=3, chunks_trough=4, lanes=8,
                min_events=10, max_events=16, initial_rps=100.0)),
            # the ≤3% unsampled-tracing guard at smoke scale. The
            # min-over-paired-rounds estimator needs ONE clean pair;
            # shorter rounds shrink the per-pair window a host stall
            # can land in and more rounds multiply the chances of a
            # clean one — 9x1500 costs ~the same 12k paired calls as
            # the original 3x4000 with 3x the chances, after false
            # >3% readings were observed on the loaded single-core CI
            # host right after heavy suites
            "telemetry_overhead": dict(telemetry=dict(
                calls=1500, rounds=9)),
            # queue-drain JSON contract at seconds scale: shape + the
            # sequential/parallel state-equality and non-degraded
            # matrix-gate bits (speedup itself is noise-bound at this
            # scale and is only pinned > 0)
            "queue_drain": dict(qdrain=dict(
                tasks_per_queue=250, n_wf=16, parallelism=4,
                batch_size=64)),
        }

    copy_bw = measure_copy_bw_gbps() if not on_cpu else None

    # headline first; if the wall-clock budget runs out (cold compile
    # cache), the JSON line still carries the metric that matters and
    # marks the rest skipped
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    # never *start* a non-headline config that could straddle the
    # watchdog wall: a cold-compile config can eat the whole slack and
    # turn an otherwise-healthy run into an error record
    wall_margin_s = 480.0
    # rebuild_warm right after the headline: the checkpoint-resume
    # record (hit rate / suffix_frac / vs_cold) must not fall to the
    # wall-clock budget skip that trims trailing configs
    front = [k for k in ("retry_deep", "rebuild_warm") if k in CONFIGS]
    order = front + [k for k in CONFIGS if k not in front]
    t_start = time.perf_counter()
    results = _PARTIAL
    for config in order:
        cfg = CONFIGS[config]
        elapsed = time.perf_counter() - t_start
        if config != "retry_deep" and (
            elapsed > budget_s or elapsed > wall_s - wall_margin_s
        ):
            results[config] = {"skipped": "bench budget exhausted"}
            continue
        if "reshard" in cfg:
            try:
                results[config] = _bench_reshard_live(**cfg["reshard"])
            except Exception as e:  # a wedged box must not eat the record
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "lag" in cfg:
            try:
                results[config] = _bench_replication_lag(**cfg["lag"])
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "failover" in cfg:
            try:
                results[config] = _bench_failover_drill(**cfg["failover"])
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "serve" in cfg:
            try:
                results[config] = _bench_serve_continuous(**cfg["serve"])
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "overload" in cfg:
            try:
                results[config] = _bench_serve_overload(**cfg["overload"])
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "diurnal" in cfg:
            try:
                results[config] = _bench_capacity_diurnal(
                    **cfg["diurnal"]
                )
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "telemetry" in cfg:
            try:
                results[config] = _bench_telemetry_overhead(
                    **cfg["telemetry"]
                )
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "qdrain" in cfg:
            try:
                results[config] = _bench_queue_drain(**cfg["qdrain"])
            except Exception as e:
                results[config] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        elif "warm" in cfg:
            results[config] = _bench_rebuild_warm(
                cfg["warm"]["n"], cfg["warm"]["depth"],
                cfg["warm"]["iters"])
        elif "packed" in cfg:
            results[config] = _bench_config_packed(
                config, cfg["caps"], cfg["packed"]["lanes"],
                cfg["packed"]["lane_len"], iters, cfg["baseline"])
        else:
            results[config] = _bench_config(
                config, cfg["caps"], cfg["batch"], iters, cfg["baseline"],
                bt, tb, use_pallas,
                chain=int(os.environ.get(
                    "BENCH_CHAIN",
                    "4" if (config == "retry_deep" and use_pallas) else "1",
                )),
                depth_curve=(config == "retry_deep"))

    head = results["retry_deep"]
    out = {
        "metric": "histories_replayed_per_sec_at_1k_depth",
        "value": head["histories_per_sec"],
        "unit": "histories/s",
        "vs_baseline": head["vs_baseline"],
        "baseline": "native C++ -O3 sequential replayer (same semantics, same data)",
        "kernel": head["kernel"],
        "batch_rebuild_ms_per_1k_history": round(
            head["batch_rebuild_ms"] / head["batch"], 4),
        "on_cpu": on_cpu,
        "configs": results,
    }
    out["backend"] = backend
    if backend_note:
        out["backend_note"] = backend_note
    if SMOKE:
        out["smoke"] = True
    if copy_bw is not None:
        out["copy_bw_gbps"] = round(copy_bw, 1)
    _emit(out)


if __name__ == "__main__":
    try:
        main()
    except BaseException as exc:  # the record must exist no matter what
        _emit(_fail_record(f"{type(exc).__name__}: {str(exc)[:300]}"))
    # the record is out (flushed); skip interpreter teardown — XLA:CPU's
    # executable destructors can segfault at exit under memory pressure,
    # which would turn a perfectly good record into returncode -11
    os._exit(0)
