"""North-star benchmark: batched history-replay throughput vs a compiled
host baseline, across the five BASELINE.md workload configurations.

One device step = replay scan + vectorized task refresh, i.e. the full
rebuild semantics of the reference's nDCStateRebuilder.rebuild
(/root/reference/service/history/nDCStateRebuilder.go:92-160: replay all
batches, then taskRefresher.refreshTasks).

Baseline: ``native.replay_sequential`` — the C++ (-O3) sequential
replayer in native/sidecar.cpp, one workflow and one event at a time
with bit-identical transition semantics (differential-tested in
tests/test_native_replayer.py). This is the compiled stand-in for the
reference's Go stateBuilder.applyEvents loop
(/root/reference/service/history/stateBuilder.go:112-613) — measured on
this host, on the same packed tensors, so ``vs_baseline`` compares the
same computation on the same data. If anything it is a *stronger*
baseline than Go, which replays into pointer-heavy structs and maps.

Workload configs (BASELINE.md / reference canary/const.go:64-84):
  echo        1k-class workflows, ~11-event histories
  signal      signal-heavy ragged histories
  timer_storm timer-fire-dominated streams
  retry_deep  ~1k-event activity-retry histories (the headline config)
  ndc_storm   mixed fuzzer histories + ICI snapshot exchange

Prints ONE JSON line: the headline metric (histories/s at ~1k-event
depth, vs_baseline against the C++ replayer) plus per-config numbers and
p50 batched-rebuild latency under "configs".
"""

from __future__ import annotations

import json
import random
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

if "--cpu" in sys.argv:
    # the axon plugin bootstrap rewrites JAX_PLATFORMS; pin via config
    jax.config.update("jax_platforms", "cpu")


def _build_histories(config: str, n_unique: int, caps):
    from cadence_tpu.testing import workloads as W
    from cadence_tpu.testing.event_generator import HistoryFuzzer

    rng = random.Random(42)
    fz = HistoryFuzzer(seed=42, caps=caps)
    out = []
    for i in range(n_unique):
        if config == "echo":
            b = W.echo_history()
        elif config == "signal":
            b = W.signal_history(rng)
        elif config == "timer_storm":
            b = W.timer_storm_history(rng, depth=400)
        elif config == "retry_deep":
            b = W.retry_deep_history(rng, depth=1000)
        else:  # ndc_storm
            b = W.ndc_storm_history(fz, depth=1000)
        out.append((f"wf-{i}", f"run-{i}", b))
    return out


def _tile(packed, batch: int):
    """Tile a packed batch of uniques up to `batch` rows."""
    n = packed.events.shape[0]
    reps = (batch + n - 1) // n
    events = np.tile(packed.events, (reps, 1, 1))[:batch]
    lengths = np.tile(packed.lengths, reps)[:batch]
    return events, lengths


def _bench_config(config: str, caps, batch: int, iters: int,
                  baseline_histories: int):
    """Returns (device_rate, cpp_rate, mean_depth, p50_ms)."""
    from cadence_tpu import native
    from cadence_tpu.ops import schema as S
    from cadence_tpu.ops.pack import pack_histories
    from cadence_tpu.ops.refresh import refresh_tasks_device
    from cadence_tpu.ops.replay import replay_scan

    n_unique = min(32, batch)
    packed = pack_histories(_build_histories(config, n_unique, caps),
                            caps=caps)
    events, lengths = _tile(packed, batch)
    mean_depth = float(lengths.mean())
    events_tm = jnp.asarray(
        np.ascontiguousarray(np.transpose(events, (1, 0, 2)))
    )

    def step(state, ev_tm):
        final = replay_scan(state, ev_tm)
        return final, refresh_tasks_device(final)

    step_jit = jax.jit(step)
    state0 = jax.device_put(
        jax.tree_util.tree_map(jnp.asarray, S.empty_state(batch, caps))
    )
    jax.block_until_ready(state0)
    jax.block_until_ready(step_jit(state0, events_tm))  # compile

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(step_jit(state0, events_tm))
        times.append(time.perf_counter() - t0)
    times.sort()
    p50 = times[len(times) // 2]
    device_rate = batch / p50

    # compiled-host baseline: C++ sequential replay of the same tensors
    class _Sub:
        pass

    sub = _Sub()
    nb = min(baseline_histories, batch)
    sub.events = events[:nb]
    sub.lengths = lengths[:nb]
    sub.caps = caps
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < 0.5:
        native.replay_sequential(sub)
        reps += 1
    cpp_s = (time.perf_counter() - t0) / reps
    cpp_rate = nb / cpp_s

    return device_rate, cpp_rate, mean_depth, p50 * 1000.0


def main() -> None:
    from cadence_tpu import native
    from cadence_tpu.ops import schema as S

    if native._load() is None:
        print(json.dumps({"error": "native baseline unavailable (no g++)"}))
        return

    on_cpu = jax.default_backend() == "cpu"
    scale = 1 if on_cpu else 16
    iters = 3 if on_cpu else 10

    # per-config capacities: sized to the workload (slot tables directly
    # set HBM bytes/step — the scan is memory-bound on the state carry)
    CONFIGS = {
        "echo": dict(
            caps=S.Capacities(max_events=16, max_activities=2, max_timers=2,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=2, max_version_items=2),
            batch=512 * scale, baseline=2048),
        "signal": dict(
            caps=S.Capacities(max_events=512, max_activities=2, max_timers=2,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=4, max_version_items=2),
            batch=64 * scale, baseline=512),
        "timer_storm": dict(
            caps=S.Capacities(max_events=512, max_activities=2, max_timers=16,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=2, max_version_items=2),
            batch=64 * scale, baseline=512),
        "retry_deep": dict(
            caps=S.Capacities(max_events=1024, max_activities=4, max_timers=2,
                              max_children=2, max_request_cancels=2,
                              max_signals_ext=2, max_version_items=2),
            batch=32 * scale, baseline=256),
        "ndc_storm": dict(
            caps=S.Capacities(max_events=1024),  # full default tables
            batch=32 * scale, baseline=256),
    }

    results = {}
    for config, cfg in CONFIGS.items():
        dev, cpp, depth, p50_ms = _bench_config(
            config, cfg["caps"], cfg["batch"], iters, cfg["baseline"])
        results[config] = {
            "histories_per_sec": round(dev, 2),
            "baseline_cpp_per_sec": round(cpp, 2),
            "vs_baseline": round(dev / cpp, 2),
            "mean_depth": round(depth, 1),
            "p50_batch_rebuild_ms": round(p50_ms, 3),
            "batch": cfg["batch"],
        }

    head = results["retry_deep"]
    print(json.dumps({
        "metric": "histories_replayed_per_sec_at_1k_depth",
        "value": head["histories_per_sec"],
        "unit": "histories/s",
        "vs_baseline": head["vs_baseline"],
        "baseline": "native C++ -O3 sequential replayer (same semantics, same data)",
        "p50_rebuild_ms_per_1k_history": round(
            head["p50_batch_rebuild_ms"] / head["batch"], 4),
        "configs": results,
    }))


if __name__ == "__main__":
    main()
